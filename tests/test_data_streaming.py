"""Streaming ingest plane: byte budget, windowed shuffle, spill, lineage
recovery, prefetching train shards (ray_tpu/data/streaming/)."""

import gc
import glob
import os
import threading
import time

import numpy as np
import pytest

from ray_tpu import data as rd
from ray_tpu.data.context import DataContext
from ray_tpu.data.streaming import (BlockLineage, ByteBudget,
                                    ShardIterator)


# --------------------------------------------------------------------------- #
# ByteBudget
# --------------------------------------------------------------------------- #


def test_budget_admission_and_release():
    b = ByteBudget(100)
    assert b.try_acquire("map", 60)
    assert b.try_acquire("map", 30)
    # Over budget with bytes in flight: refused.
    assert not b.try_acquire("map", 30)
    b.release("map", 60)
    assert b.try_acquire("map", 30)
    stats = b.stats()
    assert stats["ops"]["map"]["blocks"] == 3
    assert stats["ops"]["map"]["bytes_hwm"] == 90
    assert stats["used_bytes"] == 60


def test_budget_progress_guarantee_admits_oversized_block():
    """A block larger than the whole budget must admit when the op has
    nothing in flight — degrade to window-at-a-time, never deadlock."""
    b = ByteBudget(10)
    assert b.try_acquire("map", 1000)
    assert not b.try_acquire("map", 1)  # now it has to wait
    b.release("map", 1000)
    assert b.try_acquire("map", 1)


def test_budget_cross_op_progress():
    """One op hogging the budget must not permanently starve another:
    the starved op (nothing in flight) is admitted over budget."""
    b = ByteBudget(100)
    assert b.try_acquire("map", 100)
    assert b.try_acquire("reduce", 50)  # progress guarantee
    assert not b.try_acquire("reduce", 10)


def test_budget_adjust_corrects_estimate():
    b = ByteBudget(100)
    b.try_acquire("map", 10)
    b.adjust("map", 40)  # sealed size turned out to be 50
    assert b.used == 50
    b.release("map", 50)
    assert b.used == 0


def test_budget_release_op_drains_charges_and_reset_drains_ledger():
    b = ByteBudget(100)
    b.try_acquire("map", 70)
    b.release_op("map")
    assert b.used == 0
    # The account survives for stats(); reset() is the full drain.
    assert b.stats()["ops"]["map"]["bytes_in_flight"] == 0
    b.reset()
    assert b.stats()["ops"] == {}


def test_budget_blocking_acquire_wakes_on_release():
    b = ByteBudget(100)
    assert b.acquire("map", 100)
    done = []

    def blocked():
        done.append(b.acquire("map", 50, timeout=5.0))

    t = threading.Thread(target=blocked, daemon=True)
    t.start()
    time.sleep(0.05)
    b.release("map", 100)
    t.join(timeout=5.0)
    assert done == [True]
    assert b.stats()["ops"]["map"]["blocked_s"] > 0


def test_budget_negotiated_respects_config(ray_start_shared):
    from ray_tpu.core.config import GLOBAL_CONFIG

    ctx = DataContext.get_current()
    old = ctx.inflight_budget_bytes
    try:
        ctx.inflight_budget_bytes = 12345
        assert ByteBudget.negotiated().total == 12345
        # None = fall through to the GLOBAL_CONFIG flag (refresh()-aware
        # memoized read); explicit set wins over the default.
        ctx.inflight_budget_bytes = None
        GLOBAL_CONFIG.data_inflight_budget_bytes = 54321
        try:
            assert ByteBudget.negotiated().total == 54321
        finally:
            GLOBAL_CONFIG._overrides.pop("data_inflight_budget_bytes", None)
        # Flag default (0) = negotiate against the store: nonzero, and no
        # bigger than the store itself.
        negotiated = ByteBudget.negotiated().total
        assert negotiated >= 64 * 1024 * 1024
    finally:
        ctx.inflight_budget_bytes = old


# --------------------------------------------------------------------------- #
# Windowed shuffle
# --------------------------------------------------------------------------- #


def test_windowed_shuffle_matches_seeded_rows(ray_start_shared):
    """A tiny budget forces multiple windows; the row-level output must
    be IDENTICAL to the same seed under a huge budget (windowing is
    invisible to determinism)."""
    ctx = DataContext.get_current()
    old = ctx.inflight_budget_bytes
    try:
        ctx.inflight_budget_bytes = 1 << 30
        wide = rd.range(300, parallelism=6).random_shuffle(seed=11)
        rows_wide = [r["id"] for r in wide.take_all()]

        ctx.inflight_budget_bytes = 4096  # a few KB: forces windows
        narrow = rd.range(300, parallelism=6).random_shuffle(seed=11)
        rows_narrow = [r["id"] for r in narrow.take_all()]
        assert rows_narrow == rows_wide
        assert sorted(rows_narrow) == list(range(300))
        assert narrow.last_shuffle_stats["windows"] > 1
        assert wide.last_shuffle_stats["windows"] == 1
    finally:
        ctx.inflight_budget_bytes = old


def test_windowed_shuffle_reexecutes_per_epoch(ray_start_shared):
    """Re-iterating a shuffled dataset RE-WINDOWS (re-runs the exchange)
    instead of reusing materialized refs — multi-epoch ingest must not
    pin the whole dataset."""
    ds = rd.range(120, parallelism=4).random_shuffle(seed=3)
    first = [r["id"] for r in ds.iter_rows()]
    stats_first = dict(ds.last_shuffle_stats)
    second = [r["id"] for r in ds.iter_rows()]
    assert sorted(first) == sorted(second) == list(range(120))
    assert first == second  # seeded: epochs agree
    assert ds._materialized_refs is None
    assert ds.last_shuffle_stats["input_blocks"] == \
        stats_first["input_blocks"]
    # materialize() still pins an epoch when asked.
    mat = ds.materialize()
    assert mat._materialized_refs is not None


def test_shuffle_backpressure_accounting(ray_start_shared):
    ctx = DataContext.get_current()
    old = ctx.inflight_budget_bytes
    try:
        ctx.inflight_budget_bytes = 4096
        ds = rd.range(200, parallelism=4).random_shuffle(seed=5)
        assert sorted(r["id"] for r in ds.take_all()) == list(range(200))
        stats = ds.stats()
        bp = stats.backpressure
        assert bp is not None
        # Stage ledger keys are instance-unique ("ShuffleMap#<n>") so
        # sibling executions sharing a budget can't cross-release.
        shuffle_ops = {op: acct for op, acct in bp["ops"].items()
                       if op.startswith("Shuffle")}
        assert shuffle_ops, bp["ops"]
        assert all(acct["bytes_in_flight"] == 0
                   for acct in shuffle_ops.values())
        assert any(acct["blocks"] > 0 for acct in bp["ops"].values())
        assert "backpressure" in repr(stats)
    finally:
        ctx.inflight_budget_bytes = old


def test_map_pipeline_budget_accounting(ray_start_shared):
    ds = rd.range(100, parallelism=4).map(lambda r: {"id": r["id"] + 1})
    assert ds.count() == 100
    bp = ds.stats().backpressure
    assert bp is not None and bp["total_bytes"] > 0
    (op_name, acct), = [kv for kv in bp["ops"].items()]
    assert acct["blocks"] == 4
    assert acct["bytes_in_flight"] == 0  # everything released


def test_shuffle_mixed_block_representations(ray_start_shared):
    """A union of columnar and row parents shuffles correctly: the
    columnar fast path's dict buckets must expand to ROWS in a mixed
    reduce partition (regression: extending the raw dict spliced column
    names into the data)."""
    cols = rd.from_numpy(np.arange(40, dtype=np.int64), column="id")
    rows = rd.from_items([{"id": int(i)} for i in range(40, 60)])
    out = cols.union(rows).random_shuffle(seed=6)
    got = sorted(int(r["id"]) for r in out.iter_rows())
    assert got == list(range(60))


# --------------------------------------------------------------------------- #
# Lineage
# --------------------------------------------------------------------------- #


def test_lineage_recompute_is_bounded(ray_start_shared):
    import ray_tpu

    def make_block(lo, hi):
        return [{"id": i} for i in range(lo, hi)]

    lineage = BlockLineage(max_recomputes_per_block=2)
    ref = ray_tpu.remote(make_block).remote(0, 5)
    lineage.record(ref, make_block, (0, 5), [])
    assert len(lineage) == 1
    new_ref = lineage.recompute(ref)
    assert ray_tpu.get(new_ref) == make_block(0, 5)
    acct = lineage.accounting()
    assert acct["dataplane_recomputed_blocks"] == 1
    # Attempt budget: the same recipe re-runs at most max_recomputes.
    newer = lineage.recompute(new_ref)
    from ray_tpu.exceptions import ObjectLostError

    with pytest.raises(ObjectLostError):
        lineage.recompute(newer)
    lineage.clear()
    assert len(lineage) == 0


def test_lineage_registry_is_bounded():
    """Recipes pin their ref args, so the registry is a bounded FIFO —
    a ref-taking consumer can't pin a whole epoch of intermediates."""
    class _FakeRef:
        def __init__(self, i):
            self.object_id = type("_O", (), {
                "binary": staticmethod(lambda i=i: b"%08d" % i)})()

    lineage = BlockLineage(max_recomputes_per_block=1)
    for i in range(BlockLineage.MAX_RECORDS + 40):
        lineage.record(_FakeRef(i), None, (i,), [])
    assert len(lineage) == BlockLineage.MAX_RECORDS


def test_executor_records_replayable_lineage_only(ray_start_shared):
    """Recipes with ObjectRef args are the core tier's business (data-tier
    records would pin upstream blocks); ref-free recipes are recorded
    while the execution runs and drain when it finishes."""
    ds = rd.range(60, parallelism=3).map(lambda r: {"id": r["id"]})
    seen = []
    for _ in ds._iter_block_refs():
        seen.append(len(ds._lineage))
    assert max(seen) > 0  # read recipes (range args) were recorded
    assert len(ds._lineage) == 0  # cleared with the execution


# --------------------------------------------------------------------------- #
# Train ingest: prefetching shards + stall accounting
# --------------------------------------------------------------------------- #


def _slow_blocks(n, delay_s, rows_per_block=8):
    for b in range(n):
        time.sleep(delay_s)
        yield {"id": np.arange(b * rows_per_block,
                               (b + 1) * rows_per_block)}


class _SlowSource:
    """Iterable block source with a per-block production delay."""

    def __init__(self, n, delay_s):
        self.n = n
        self.delay_s = delay_s

    def __iter__(self):
        return _slow_blocks(self.n, self.delay_s)


def test_shard_iterator_accounts_stall_and_steps():
    it = ShardIterator(_SlowSource(6, 0.01), prefetch=2)
    batches = list(it.iter_batches(batch_size=8))
    assert len(batches) == 6
    stats = it.ingest_stats()
    assert stats["steps"] == 6
    assert stats["epochs"] == 1
    assert stats["prefetch_depth"] == 2
    assert 0.0 <= stats["stall_frac"] <= 1.0
    assert stats["stall_ms_total"] >= 0.0


def test_shard_iterator_prefetch_hides_producer_latency():
    """Double-buffered prefetch overlaps block production with the
    consuming step: stall with prefetch on must undercut prefetch off
    (the A/B the ingest bench gates on)."""
    def consume(prefetch):
        it = ShardIterator(_SlowSource(10, 0.02), prefetch=prefetch)
        for _ in it.iter_batches(batch_size=8):
            time.sleep(0.02)  # the "step"
        return it.ingest_stats()

    stalled = consume(prefetch=0)
    overlapped = consume(prefetch=2)
    assert overlapped["stall_ms_total"] < stalled["stall_ms_total"], \
        (overlapped, stalled)


def test_shard_iterator_abandoned_consumer_reaps_pump():
    """Breaking out of iter_batches early (max_steps) must not leak the
    prefetch thread: even the terminal sentinel put yields to stop()."""
    before = {t.name for t in threading.enumerate()}
    it = ShardIterator(_SlowSource(4, 0.0), prefetch=1)
    for _ in it.iter_batches(batch_size=8):
        break  # abandon with the producer parked on a full queue
    deadline = time.monotonic() + 6
    while time.monotonic() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t.name == "ingest-prefetch" and t.name not in before]
        if not leaked:
            break
        time.sleep(0.05)
    assert not [t for t in threading.enumerate()
                if t.name == "ingest-prefetch"], "prefetch thread leaked"


def test_shard_iterator_multi_epoch_and_pickle(ray_start_shared):
    ds = rd.range(64, parallelism=4)
    shard_a, shard_b = rd.DataIterator(ds).iter_shards(2, prefetch=2)
    import cloudpickle

    shard_b = cloudpickle.loads(cloudpickle.dumps(shard_b))  # ships to a worker
    rows_a = [r["id"] for r in shard_a.iter_rows()]
    rows_b = [r["id"] for r in shard_b.iter_rows()]
    assert sorted(rows_a + rows_b) == list(range(64))
    # Second epoch re-drives the shared execution.
    rows_a2 = [r["id"] for r in shard_a.iter_rows()]
    rows_b2 = [r["id"] for r in shard_b.iter_rows()]
    assert sorted(rows_a2 + rows_b2) == list(range(64))
    assert shard_a.ingest_stats()["epochs"] == 2


def test_trainer_shards_report_ingest_stats(ray_start_shared, tmp_path):
    """The trainer hands workers prefetching ShardIterators and
    session.get_ingest_stats() surfaces the stall accounting."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig
    from ray_tpu.train.backend import JaxConfig

    def loop(config):
        from ray_tpu.train import session

        shard = session.get_dataset_shard("train")
        seen = 0
        for batch in shard.iter_batches(batch_size=8):
            seen += len(batch["id"])
        stats = session.get_ingest_stats()["train"]
        session.report({"rows": seen, "steps": stats["steps"],
                        "stall_ms": stats["stall_ms_total"],
                        "stall_frac": stats["stall_frac"]})

    result = JaxTrainer(
        loop,
        jax_config=JaxConfig(distributed=False),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="ingest_stats", storage_path=str(tmp_path)),
        datasets={"train": rd.range(64, parallelism=4)},
    ).fit()
    assert result.error is None, result.error
    assert result.metrics["steps"] > 0
    assert result.metrics["stall_frac"] <= 1.0


# --------------------------------------------------------------------------- #
# Stats collector boundedness
# --------------------------------------------------------------------------- #


def test_stats_collector_mailbox_bounded_and_prunes():
    from ray_tpu.data.stats import _StatsCollector

    c = _StatsCollector()
    # Keyed state is capped: a sender inventing unbounded op names
    # degrades to a drop counter, not unbounded actor memory.
    for i in range(c.MAX_OP_ENTRIES + 50):
        c.record([(0, f"op{i}", 0.001, 1)])
    summary = c.summary()
    assert len(summary["ops"]) == c.MAX_OP_ENTRIES
    assert summary["dropped_records"] == 50
    # Finished-op prune: per-window stage records fold into one rollup.
    c2 = _StatsCollector()
    for w in range(5):
        c2.record_stage([(-2, f"ShuffleMap[window {w}]", 0.1, 10)])
    assert len(c2.summary()["ops"]) == 5
    c2.fold(-2, "ShuffleMap")
    ops = c2.summary()["ops"]
    assert len(ops) == 1
    assert ops[0]["name"] == "ShuffleMap"
    assert ops[0]["blocks"] == 5 and ops[0]["rows"] == 50
    # record_stage never inflates the blocks_recorded flush barrier.
    assert c2.summary()["blocks_recorded"] == 0


def test_shuffle_stage_records_fold_into_rollup(ray_start_shared):
    ctx = DataContext.get_current()
    old = ctx.inflight_budget_bytes
    try:
        ctx.inflight_budget_bytes = 4096  # multiple windows
        ds = rd.range(300, parallelism=6).random_shuffle(seed=4)
        assert ds.count() == 300
        stats = ds.stats()
        assert stats is not None
        names = [op["name"] for op in stats.ops]
        assert "ShuffleMap" in names
        assert "ShuffleReduce" in names
        # Per-window records were pruned after the fold.
        assert not any("window" in n for n in names), names
    finally:
        ctx.inflight_budget_bytes = old
