"""Streaming ingest plane under its own clusters: spill through a tiny
arena, chaos node-kill recovery. Separate module: these tests build (and
tear down) dedicated clusters and must not share a module-scoped one."""

import gc
import glob
import os
import time

import pytest

from ray_tpu import data as rd


# --------------------------------------------------------------------------- #
# Spill tier under a tiny arena
# --------------------------------------------------------------------------- #


def _shm_segments(session_suffix: str):
    """Live (non-pool, non-staging) store segments of this session."""
    return [p for p in glob.glob(f"/dev/shm/rtpu_{session_suffix}_*")
            if "_pool" not in os.path.basename(p)]


def test_full_shuffle_epoch_spills_not_oom():
    """A shuffle whose working set exceeds a tiny store arena completes
    via the spill tier: full epoch, rows exact, `num_unsealed == 0`, and
    zero leaked segments after the refs drop."""
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, object_store_memory=3 * 1024 * 1024)
    try:
        node = ray_tpu._global_node
        store = node.raylet.store
        # Blocks must clear the inline threshold (100 KiB) or they never
        # touch the store: 4 blocks x ~1 MiB of tensor rows, working set
        # (inputs + buckets + outputs) ~3x the 3 MiB arena.
        ds = rd.range_tensor(8000, shape=(16,), parallelism=4) \
            .random_shuffle(seed=2)
        total = 0
        for batch in ds.iter_batches(batch_size=500):
            total += len(batch["data"])
        assert total == 8000
        stats = store.stats()
        assert stats["num_unsealed"] == 0, stats
        # The arena could not have held the epoch: spill carried it.
        assert stats["used_bytes"] <= store.capacity
        # Drop the pipeline; every segment must drain (frees are batched
        # on a 1s timer, so poll with a deadline).
        del ds
        gc.collect()
        deadline = time.monotonic() + 15
        session = node.session_suffix
        while time.monotonic() < deadline:
            if not _shm_segments(session) and \
                    store.stats()["num_unsealed"] == 0:
                break
            time.sleep(0.2)
        leaked = _shm_segments(session)
        assert not leaked, f"leaked segments: {leaked}"
    finally:
        ray_tpu.shutdown()


@pytest.mark.slow  # multi-node cluster + recovery: >10s under load; the
# gate's `bench.py --ingest-smoke` hard-gates the same scenario
def test_node_death_mid_shuffle_recomputes_bounded():
    """Chaos: kill a node mid-shuffle. The epoch completes, recomputed
    blocks are bounded by the dead node's resident blocks (never a
    whole-pipeline restart), and nothing hangs."""
    import ray_tpu
    from ray_tpu.chaos import HangWatchdog
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.data.streaming.lineage import core_reconstructions

    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    for _ in range(2):
        cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    cluster.connect()
    try:
        n_parts = 8
        ds = rd.range_tensor(4000, shape=(40,), parallelism=n_parts) \
            .random_shuffle(seed=9)
        base = core_reconstructions()
        rows = 0
        killed = {}
        with HangWatchdog(limit_s=60.0) as wd:
            for i, batch in enumerate(ds.iter_batches(batch_size=250)):
                rows += len(batch["data"])
                if i == 1 and not killed:
                    # Kill the worker node holding the most blocks so the
                    # fault actually destroys state the pipeline needs.
                    victim = max(
                        (r for r in cluster.raylets if not r.is_head),
                        key=lambda r: r.store.stats()["num_objects"])
                    killed["resident"] = \
                        victim.store.stats()["num_objects"]
                    cluster.crash_node(victim)
        wd.assert_no_hangs()
        assert rows == 4000
        recomputed = (core_reconstructions() - base) \
            + ds._lineage.recomputed_blocks if ds._lineage else 0
        total_blocks = killed["resident"] if killed else 0
        # Bounded: no more re-executions than the victim held blocks
        # (map buckets + reduce outputs), and certainly not a restart of
        # every task in the pipeline.
        assert recomputed <= max(total_blocks, 1) + n_parts, \
            (recomputed, killed)
        for raylet in cluster.raylets:
            assert raylet.store.stats()["num_unsealed"] == 0
    finally:
        try:
            cluster.shutdown()
        except Exception:  # noqa: BLE001 — nodes already churned
            pass


