"""Direct (lease-cached) task transport.

The owner requests worker leases from the raylet and pushes eligible
normal tasks straight to the leased worker (reference
`direct_task_transport.h:75,151`); these tests pin down eligibility,
lease lifecycle (grant/reuse/idle-return/cancel), failure handling, and
result visibility for directly-executed tasks.
"""

import time

import pytest

import ray_tpu
from ray_tpu.core.config import GLOBAL_CONFIG


@pytest.fixture()
def ray_direct():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def _transport():
    return ray_tpu._require_runtime()._direct


def _raylet():
    return ray_tpu._global_node.raylet


def test_direct_path_engages_and_reuses_lease(ray_direct):
    @ray_tpu.remote
    def f(x):
        import os

        return (x, os.getpid())

    out = ray_tpu.get([f.remote(i) for i in range(20)])
    assert [x for x, _ in out] == list(range(20))
    # The lease cache served these: leases exist (or just returned), and
    # at most num_cpus distinct workers ran 20 tasks.
    assert len({pid for _, pid in out}) <= 2
    d = _transport()
    assert sum(len(v) for v in d._leases.values()) >= 1


def test_idle_leases_returned_and_requests_cancelled(ray_direct):
    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get([f.remote() for _ in range(10)])
    d = _transport()
    deadline = time.monotonic() + GLOBAL_CONFIG.direct_lease_idle_s + 5
    while time.monotonic() < deadline:
        leases = sum(len(v) for v in d._leases.values())
        reqs = len(d._inflight_reqs)
        if leases == 0 and reqs == 0:
            break
        time.sleep(0.2)
    assert sum(len(v) for v in d._leases.values()) == 0
    assert len(d._inflight_reqs) == 0
    # The raylet agrees: no lease records, no queued lease requests.
    raylet = _raylet()
    assert not raylet._leases
    assert not any(qt.lease_req_id is not None for qt in raylet._queue)
    # And fresh work after the idle window completes promptly.
    t0 = time.monotonic()
    assert ray_tpu.get(f.remote(), timeout=30) == 1
    assert time.monotonic() - t0 < 10


def test_direct_results_usable_as_deps(ray_direct):
    @ray_tpu.remote
    def produce():
        return 41

    @ray_tpu.remote
    def consume(x):
        return x + 1

    r = produce.remote()
    # Dep resolved -> the consumer is itself direct-eligible.
    ray_tpu.wait([r], num_returns=1, timeout=30)
    assert ray_tpu.get(consume.remote(r), timeout=30) == 42


def test_direct_task_error_propagates(ray_direct):
    @ray_tpu.remote
    def boom():
        raise ValueError("direct boom")

    with pytest.raises(ValueError, match="direct boom"):
        ray_tpu.get(boom.remote(), timeout=30)


def test_direct_task_worker_crash_retries(ray_direct):
    import os

    @ray_tpu.remote(max_retries=2)
    def flaky(path):
        if not os.path.exists(path):
            open(path, "w").close()
            os._exit(1)  # kill the leased worker mid-task
        return "recovered"

    import tempfile

    marker = os.path.join(tempfile.mkdtemp(), "flaky_marker")
    assert ray_tpu.get(flaky.remote(marker), timeout=60) == "recovered"


def test_direct_task_worker_crash_exhausts_retries(ray_direct):
    import os

    @ray_tpu.remote(max_retries=0)
    def die():
        os._exit(1)

    from ray_tpu.exceptions import WorkerCrashedError

    with pytest.raises(WorkerCrashedError):
        ray_tpu.get(die.remote(), timeout=60)


def test_cancel_running_direct_task(ray_direct):
    @ray_tpu.remote
    def sleeper():
        time.sleep(60)
        return "done"

    from ray_tpu.exceptions import TaskCancelledError

    ref = sleeper.remote()
    time.sleep(1.0)  # let it start on the leased worker
    ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=30)


def test_ineligible_tasks_take_classic_path(ray_direct):
    from ray_tpu.util.scheduling_strategies import SpreadSchedulingStrategy

    @ray_tpu.remote(scheduling_strategy=SpreadSchedulingStrategy())
    def spread():
        return "classic"

    assert ray_tpu.get(spread.remote(), timeout=30) == "classic"
    d = _transport()
    # A strategy task never enters the direct queues.
    assert all(not p for p in d._pending.values())


def test_direct_disabled_flag_falls_back(ray_direct):
    old = GLOBAL_CONFIG.direct_task_enabled
    GLOBAL_CONFIG.direct_task_enabled = False
    try:
        @ray_tpu.remote
        def f():
            return 7

        assert ray_tpu.get(f.remote(), timeout=30) == 7
    finally:
        GLOBAL_CONFIG.direct_task_enabled = old


def test_direct_timeline_events_recorded(ray_direct):
    @ray_tpu.remote
    def traced_direct():
        return 1

    ray_tpu.get([traced_direct.remote() for _ in range(3)])
    deadline = time.monotonic() + 15
    finished = 0
    while time.monotonic() < deadline:
        events = ray_tpu.timeline()
        finished = sum(1 for e in events
                       if "traced_direct" in e.get("name", "")
                       and e.get("state") == "FINISHED")
        if finished >= 3:
            break
        time.sleep(0.3)
    assert finished >= 3
