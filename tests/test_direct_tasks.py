"""Direct (lease-cached) task transport.

The owner requests worker leases from the raylet and pushes eligible
normal tasks straight to the leased worker (reference
`direct_task_transport.h:75,151`); these tests pin down eligibility,
lease lifecycle (grant/reuse/idle-return/cancel), failure handling, and
result visibility for directly-executed tasks.
"""

import time

import pytest

import ray_tpu
from ray_tpu.core.config import GLOBAL_CONFIG


@pytest.fixture()
def ray_direct():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def _transport():
    return ray_tpu._require_runtime()._direct


def _raylet():
    return ray_tpu._global_node.raylet


def test_direct_path_engages_and_reuses_lease(ray_direct):
    @ray_tpu.remote
    def f(x):
        import os

        return (x, os.getpid())

    out = ray_tpu.get([f.remote(i) for i in range(20)])
    assert [x for x, _ in out] == list(range(20))
    # The lease cache served these: leases exist (or just returned), and
    # at most num_cpus distinct workers ran 20 tasks.
    assert len({pid for _, pid in out}) <= 2
    d = _transport()
    assert sum(len(v) for v in d._leases.values()) >= 1


def test_idle_leases_returned_and_requests_cancelled(ray_direct):
    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get([f.remote() for _ in range(10)])
    d = _transport()
    deadline = time.monotonic() + GLOBAL_CONFIG.direct_lease_idle_s + 5
    while time.monotonic() < deadline:
        leases = sum(len(v) for v in d._leases.values())
        reqs = len(d._inflight_reqs)
        if leases == 0 and reqs == 0:
            break
        time.sleep(0.2)
    assert sum(len(v) for v in d._leases.values()) == 0
    assert len(d._inflight_reqs) == 0
    # The raylet agrees: no lease records, no queued lease requests.
    raylet = _raylet()
    assert not raylet._leases
    assert not any(qt.lease_req_id is not None for qt in raylet._queue)
    # And fresh work after the idle window completes promptly.
    t0 = time.monotonic()
    assert ray_tpu.get(f.remote(), timeout=30) == 1
    assert time.monotonic() - t0 < 10


def test_direct_results_usable_as_deps(ray_direct):
    @ray_tpu.remote
    def produce():
        return 41

    @ray_tpu.remote
    def consume(x):
        return x + 1

    r = produce.remote()
    # Dep resolved -> the consumer is itself direct-eligible.
    ray_tpu.wait([r], num_returns=1, timeout=30)
    assert ray_tpu.get(consume.remote(r), timeout=30) == 42


def test_direct_task_error_propagates(ray_direct):
    @ray_tpu.remote
    def boom():
        raise ValueError("direct boom")

    with pytest.raises(ValueError, match="direct boom"):
        ray_tpu.get(boom.remote(), timeout=30)


def test_direct_task_worker_crash_retries(ray_direct):
    import os

    @ray_tpu.remote(max_retries=2)
    def flaky(path):
        if not os.path.exists(path):
            open(path, "w").close()
            os._exit(1)  # kill the leased worker mid-task
        return "recovered"

    import tempfile

    marker = os.path.join(tempfile.mkdtemp(), "flaky_marker")
    assert ray_tpu.get(flaky.remote(marker), timeout=60) == "recovered"


def test_direct_task_worker_crash_exhausts_retries(ray_direct):
    import os

    @ray_tpu.remote(max_retries=0)
    def die():
        os._exit(1)

    from ray_tpu.exceptions import WorkerCrashedError

    with pytest.raises(WorkerCrashedError):
        ray_tpu.get(die.remote(), timeout=60)


def test_cancel_running_direct_task(ray_direct):
    @ray_tpu.remote
    def sleeper():
        time.sleep(60)
        return "done"

    from ray_tpu.exceptions import TaskCancelledError

    ref = sleeper.remote()
    time.sleep(1.0)  # let it start on the leased worker
    ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=30)


def test_ineligible_tasks_take_classic_path(ray_direct):
    from ray_tpu.util.scheduling_strategies import SpreadSchedulingStrategy

    @ray_tpu.remote(scheduling_strategy=SpreadSchedulingStrategy())
    def spread():
        return "classic"

    assert ray_tpu.get(spread.remote(), timeout=30) == "classic"
    d = _transport()
    # A strategy task never enters the direct queues.
    assert all(not p for p in d._pending.values())


def test_direct_disabled_flag_falls_back(ray_direct):
    old = GLOBAL_CONFIG.direct_task_enabled
    GLOBAL_CONFIG.direct_task_enabled = False
    try:
        @ray_tpu.remote
        def f():
            return 7

        assert ray_tpu.get(f.remote(), timeout=30) == 7
    finally:
        GLOBAL_CONFIG.direct_task_enabled = old


def test_direct_timeline_events_recorded(ray_direct):
    @ray_tpu.remote
    def traced_direct():
        return 1

    ray_tpu.get([traced_direct.remote() for _ in range(3)])
    deadline = time.monotonic() + 15
    finished = 0
    while time.monotonic() < deadline:
        events = ray_tpu.timeline()
        finished = sum(1 for e in events
                       if "traced_direct" in e.get("name", "")
                       and e.get("state") == "FINISHED")
        if finished >= 3:
            break
        time.sleep(0.3)
    assert finished >= 3


# ----------------------------------------------------- lease churn (PR 12)


def test_lease_steal_across_keys(ray_direct):
    """A backlogged scheduling key adopts another key's idle cached lease
    when the grant covers its demand — no raylet round trip."""

    @ray_tpu.remote
    def full():
        return "full"

    @ray_tpu.remote(num_cpus=0.5)
    def half():
        return "half"

    # Warm leases under the CPU:1 key.
    assert ray_tpu.get([full.remote() for _ in range(8)],
                       timeout=30) == ["full"] * 8
    d = _transport()
    before = dict(d.stats)
    # CPU:0.5 demand is covered by an idle CPU:1 grant: the transport may
    # steal it instead of asking the raylet for a new lease.
    assert ray_tpu.get([half.remote() for _ in range(8)],
                       timeout=30) == ["half"] * 8
    assert d.stats["lease_steals"] > before["lease_steals"], \
        "covered cross-key submission did not reuse the warm lease"


def test_lease_steal_disabled_flag(ray_direct):
    """direct_lease_steal=False: keys never share leases (the off-path
    inertness contract for the steal optimization)."""
    old = GLOBAL_CONFIG.direct_lease_steal
    GLOBAL_CONFIG.direct_lease_steal = False
    try:
        @ray_tpu.remote
        def full():
            return 1

        @ray_tpu.remote(num_cpus=0.5)
        def half():
            return 2

        ray_tpu.get([full.remote() for _ in range(4)], timeout=30)
        d = _transport()
        before = d.stats["lease_steals"]
        ray_tpu.get([half.remote() for _ in range(4)], timeout=30)
        assert d.stats["lease_steals"] == before
    finally:
        GLOBAL_CONFIG.direct_lease_steal = old


def test_lease_steal_vs_idle_return_race(ray_direct):
    """Leases sitting at the idle boundary while a compatible key goes
    hungry: whichever side wins (reaper return vs steal/rebalance), every
    task completes and the lease table stays consistent."""
    old_idle = GLOBAL_CONFIG.direct_lease_idle_s
    GLOBAL_CONFIG.direct_lease_idle_s = 0.3
    try:
        @ray_tpu.remote
        def warm():
            return "w"

        @ray_tpu.remote(num_cpus=0.5)
        def hungry():
            return "h"

        d = _transport()
        for round_ in range(6):
            assert ray_tpu.get([warm.remote() for _ in range(4)],
                               timeout=30) == ["w"] * 4
            # Land the cross-key burst right at the idle deadline: some
            # rounds the reaper returns first, some rounds the steal wins.
            time.sleep(0.3 if round_ % 2 else 0.25)
            assert ray_tpu.get([hungry.remote() for _ in range(4)],
                               timeout=30) == ["h"] * 4
        with d._lock:
            for key, leases in d._leases.items():
                for lease in leases:
                    assert not lease.closed, \
                        "closed lease left in the cache (steal/return race)"
                    assert lease.key == key, "lease filed under wrong key"
    finally:
        GLOBAL_CONFIG.direct_lease_idle_s = old_idle


def test_arg_dedupe_serializes_shared_args_once(ray_direct):
    """Small immutable args hit the owner-side blob cache: repeat
    submissions reuse one serialization, and the values stay correct."""

    @ray_tpu.remote
    def check(a, b, c, d, e):
        return (a, b, c, d, e)

    rt = ray_tpu._require_runtime()
    rt._arg_blob_cache.clear()
    out = ray_tpu.get([check.remote(7, 2.5, "shared", b"blob", None)
                       for _ in range(20)], timeout=30)
    assert out == [(7, 2.5, "shared", b"blob", None)] * 20
    # One cache entry per distinct (type, value) leaf — not per spec.
    assert 0 < len(rt._arg_blob_cache) <= 8
    # Mutable args must NOT be deduped (each spec needs its own copy).
    @ray_tpu.remote
    def mutate(lst):
        lst.append(1)
        return len(lst)

    assert ray_tpu.get([mutate.remote([0]) for _ in range(4)],
                       timeout=30) == [2] * 4


def test_flush_tick_zero_is_inert():
    """direct_flush_tick_ms=0: submits pump inline on the caller thread
    and the flusher machinery never engages (the A-B-A off-path
    contract). Multi-spec frames from backlog pumping are PRE-existing
    behavior (PR-7 coalescing) and allowed either way."""
    ray_tpu.shutdown()
    old = GLOBAL_CONFIG.direct_flush_tick_ms
    GLOBAL_CONFIG.direct_flush_tick_ms = 0.0
    try:
        ray_tpu.init(num_cpus=2)

        @ray_tpu.remote
        def f(i):
            return i * 3

        d = _transport()
        assert ray_tpu.get([f.remote(i) for i in range(16)],
                           timeout=30) == [i * 3 for i in range(16)]
        assert d._flusher is None, \
            "flush tick disabled but the flusher thread engaged"
    finally:
        GLOBAL_CONFIG.direct_flush_tick_ms = old
        ray_tpu.shutdown()


def test_batched_submission_coalesces_frames(ray_direct):
    """With the flush tick on, a burst rides multi-spec frames (the
    whole point of the pipeline) and still resolves correctly."""
    @ray_tpu.remote
    def f(i):
        return i + 100

    d = _transport()
    # One .remote() burst wide enough that the flusher sees a backlog.
    refs = [f.remote(i) for i in range(200)]
    assert ray_tpu.get(refs, timeout=60) == [i + 100 for i in range(200)]
    assert d.stats["batch_frames"] > 0, \
        "200-task burst never coalesced into a multi-spec frame"
    assert d.stats["batched_specs"] >= 2 * d.stats["batch_frames"]
