"""Ecosystem shims: multiprocessing Pool, ParallelIterator, joblib backend
(reference `python/ray/util/{multiprocessing,iter,joblib}`)."""

import numpy as np
import pytest

import ray_tpu


def _sq(x):
    return x * x


def _add(a, b):
    return a + b


def test_pool_map_and_apply(ray_start_shared):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=4) as pool:
        assert pool.map(_sq, range(10)) == [x * x for x in range(10)]
        assert pool.apply(_add, (3, 4)) == 7
        r = pool.apply_async(_add, (1, 2))
        assert r.get(timeout=30) == 3
        assert r.successful()
        assert pool.starmap(_add, [(1, 2), (3, 4)]) == [3, 7]


def test_pool_imap_orders(ray_start_shared):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=4) as pool:
        assert list(pool.imap(_sq, range(8), chunksize=2)) \
            == [x * x for x in range(8)]
        assert sorted(pool.imap_unordered(_sq, range(8), chunksize=2)) \
            == sorted(x * x for x in range(8))


def test_pool_async_error_and_close(ray_start_shared):
    from ray_tpu.util.multiprocessing import Pool

    def boom(x):
        raise ValueError("boom")

    pool = Pool(processes=2)
    r = pool.map_async(boom, [1, 2])
    with pytest.raises(ValueError):
        r.get(timeout=30)
    pool.close()
    with pytest.raises(ValueError):
        pool.map(_sq, [1])
    pool.join()


def test_parallel_iterator_transforms(ray_start_shared):
    from ray_tpu.util import iter as rit

    it = rit.from_range(12, num_shards=3).for_each(lambda x: x * 2) \
        .filter(lambda x: x % 3 == 0)
    got = sorted(it.gather_sync())
    assert got == sorted(x * 2 for x in range(12) if (x * 2) % 3 == 0)

    batches = list(rit.from_items(list(range(6)), num_shards=2)
                   .batch(2).gather_sync())
    assert all(len(b) <= 2 for b in batches)
    assert sorted(x for b in batches for x in b) == list(range(6))

    flat = sorted(rit.from_items([[1, 2], [3], [4, 5]], num_shards=2)
                  .flatten().gather_async())
    assert flat == [1, 2, 3, 4, 5]


def test_parallel_iterator_union_take(ray_start_shared):
    from ray_tpu.util import iter as rit

    a = rit.from_items([1, 2, 3], num_shards=1)
    b = rit.from_items([10, 20], num_shards=1)
    u = a.union(b)
    assert u.num_shards() == 2
    assert sorted(u.gather_sync()) == [1, 2, 3, 10, 20]
    assert len(a.take(2)) == 2


def _inv(x):
    return 1 // x


def test_joblib_backend(ray_start_shared):
    import joblib

    from ray_tpu.util.joblib import register_ray

    register_ray()
    with joblib.parallel_backend("ray", n_jobs=4):
        out = joblib.Parallel()(joblib.delayed(_sq)(i) for i in range(12))
    assert out == [i * i for i in range(12)]
    # Errors inside remote batches surface as the original exception type.
    with joblib.parallel_backend("ray", n_jobs=2):
        with pytest.raises(ZeroDivisionError):
            joblib.Parallel()(joblib.delayed(_inv)(i) for i in [1, 0])


def _stamped_sleep(x):
    import time as _t

    start = _t.monotonic()
    _t.sleep(0.4)
    return (start, _t.monotonic())


def test_pool_bounds_concurrency(ray_start_shared):
    """processes=2 really limits parallelism: no instant where more than
    two chunk tasks overlap."""
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=2) as pool:
        pool.map(_sq, range(4))  # warm the worker pool
        spans = pool.map(_stamped_sleep, range(6), chunksize=1)
    for t in {s for span in spans for s in span}:
        overlap = sum(1 for a, b in spans if a < t < b)
        assert overlap <= 2, f"{overlap} chunks ran concurrently"


def test_dynamic_resources(ray_start_regular):
    """set_resource adds capacity at runtime and queued tasks dispatch
    (reference experimental/dynamic_resources.py)."""
    import threading
    import time

    import ray_tpu
    from ray_tpu.experimental import set_resource

    @ray_tpu.remote(resources={"widget": 1})
    def needs_widget():
        return "ran"

    ref = needs_widget.remote()
    done, pending = ray_tpu.wait([ref], timeout=1.0)
    assert not done, "task ran without the resource existing"
    set_resource("widget", 2)
    assert ray_tpu.get(ref, timeout=30) == "ran"
    # Capacity shows in the cluster view and can be removed again.
    time.sleep(1.5)  # heartbeat-carried
    total = {r: v for n in ray_tpu.nodes() for r, v in n["Resources"].items()}
    assert total.get("widget") == 2
    set_resource("widget", 0)
    import pytest

    with pytest.raises(Exception, match="built-in"):
        set_resource("CPU", 64)


def test_tqdm_ray_in_worker(ray_start_regular, capsys):
    import ray_tpu
    from ray_tpu.experimental import tqdm_ray

    @ray_tpu.remote
    def work():
        out = 0
        for i in tqdm_ray.tqdm(range(50), desc="crunch",
                               flush_interval_s=0.0):
            out += i
        return out

    assert ray_tpu.get(work.remote()) == sum(range(50))
    # Local (driver-side) use prints rate-limited lines.
    bar = tqdm_ray.tqdm(total=10, desc="local", flush_interval_s=0.0)
    for _ in range(10):
        bar.update()
    bar.close()
    captured = capsys.readouterr()
    assert "local" in captured.out and "10/10" in captured.out
