"""Scalability-envelope smoke (reference release/benchmarks/README.md).

The real numbers come from `python bench.py` (bench_envelope); this
keeps the envelope harness itself from rotting, at toy sizes.
"""


def test_envelope_smoke():
    import bench

    out = bench._envelope_main(60, 4, 3, 40, 8)
    assert out["envelope_tasks"] == 60
    assert out["envelope_task_throughput_per_s"] > 0
    assert out["envelope_get_many_refs_s"] >= 0
    assert out["envelope_actors"] == 4
    assert out["envelope_pgs"] == 3
    assert out["envelope_broadcast_nodes"] >= 1
    assert out["envelope_broadcast_gb_s"] > 0
