"""Scalability-envelope smoke (reference release/benchmarks/README.md).

The real numbers come from `python bench.py` (bench_envelope); this
keeps the envelope harness itself from rotting, at toy sizes. Runs in a
subprocess for the same reason bench_envelope does: the fake cluster
would otherwise collide with the pytest process's shared global runtime.
"""

import json
import os
import subprocess
import sys


def test_envelope_smoke():
    code = ("import bench, json; "
            "print('ENV_RESULT ' + json.dumps("
            "bench._envelope_main(60, 4, 3, 40, 8)))")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["RAY_TPU_JAX_PLATFORM"] = "cpu"
    env["RAY_TPU_WORKER_LEASE_TIMEOUT_MS"] = "180000"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, "-c", code], env=env, cwd=repo,
                          capture_output=True, text=True, timeout=600)
    out = None
    for line in (proc.stdout or "").splitlines():
        if line.startswith("ENV_RESULT "):
            out = json.loads(line[len("ENV_RESULT "):])
    assert out is not None, (proc.stderr or "")[-800:]
    assert out["envelope_tasks"] == 60
    assert out["envelope_task_throughput_per_s"] > 0
    assert out["envelope_get_many_refs_s"] >= 0
    assert out["envelope_actors"] == 4
    assert out["envelope_pgs"] == 3
    assert out["envelope_broadcast_nodes"] >= 1
    assert out["envelope_broadcast_gb_s"] > 0
