"""Fault tolerance under injected failures: GCS restart, node churn.

Mirrors the reference's GCS fault-tolerance tests
(`python/ray/tests/test_gcs_fault_tolerance.py`) and NodeKiller-based
chaos tests (`test_utils.py:1367`).
"""

import os
import tempfile
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster, NodeKiller


@pytest.fixture()
def persistent_cluster():
    ray_tpu.shutdown()
    path = os.path.join(tempfile.mkdtemp(), "gcs_tables.bin")
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2},
                      gcs_storage_path=path)
    cluster.wait_for_nodes()
    cluster.connect()
    yield cluster
    cluster.shutdown()


class Counter:
    def __init__(self):
        self.n = 0

    def bump(self):
        self.n += 1
        return self.n


def test_gcs_restart_preserves_cluster(persistent_cluster):
    """GCS dies and comes back at the same address with persisted tables:
    the named actor survives, its state is intact, and new tasks run."""
    cluster = persistent_cluster
    actor_cls = ray_tpu.remote(Counter)
    counter = actor_cls.options(name="survivor", lifetime="detached").remote()
    assert ray_tpu.get(counter.bump.remote()) == 1

    cluster.kill_gcs()
    time.sleep(0.3)
    cluster.restart_gcs()

    # Raylet + driver reconnect on their next calls; give heartbeats a beat.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            alive = [n for n in cluster.gcs.handle_get_nodes(None)
                     if n["Alive"]]
            if alive:
                break
        except Exception:
            pass
        time.sleep(0.2)
    assert alive, "no node re-registered with the restarted GCS"

    # Live actor handle still works (direct connection was never broken).
    assert ray_tpu.get(counter.bump.remote(), timeout=30) == 2
    # Named lookup resolves from the RESTORED actor table.
    again = ray_tpu.get_actor("survivor")
    assert ray_tpu.get(again.bump.remote(), timeout=30) == 3

    # Fresh task submission end-to-end after failover.
    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(41), timeout=60) == 42


def test_workload_survives_node_churn():
    """Chaos: tasks with retries keep completing while NodeKiller cycles
    worker nodes out from under them."""
    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    try:
        for _ in range(2):
            cluster.add_node(num_cpus=2, resources={"churn": 2})
        cluster.wait_for_nodes()
        cluster.connect()

        @ray_tpu.remote
        def slow_square(x):
            time.sleep(0.2)
            return x * x

        opts = {"resources": {"churn": 1}, "max_retries": 8}
        with NodeKiller(cluster, period_s=1.5, max_kills=3,
                        node_args={"num_cpus": 2,
                                   "resources": {"churn": 2}}) as killer:
            results = ray_tpu.get(
                [slow_square.options(**opts).remote(i) for i in range(24)],
                timeout=180)
        assert results == [i * i for i in range(24)]
        assert killer.kills >= 1, "chaos never fired"
    finally:
        cluster.shutdown()
