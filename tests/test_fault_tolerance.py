"""Fault tolerance under injected failures: GCS restart, node churn.

Mirrors the reference's GCS fault-tolerance tests
(`python/ray/tests/test_gcs_fault_tolerance.py`) and NodeKiller-based
chaos tests (`test_utils.py:1367`).
"""

import os
import tempfile
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster, NodeKiller


@pytest.fixture()
def persistent_cluster():
    ray_tpu.shutdown()
    path = os.path.join(tempfile.mkdtemp(), "gcs_tables.bin")
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2},
                      gcs_storage_path=path)
    cluster.wait_for_nodes()
    cluster.connect()
    yield cluster
    cluster.shutdown()


class Counter:
    def __init__(self):
        self.n = 0

    def bump(self):
        self.n += 1
        return self.n


def test_gcs_restart_preserves_cluster(persistent_cluster):
    """GCS dies and comes back at the same address with persisted tables:
    the named actor survives, its state is intact, and new tasks run."""
    cluster = persistent_cluster
    actor_cls = ray_tpu.remote(Counter)
    counter = actor_cls.options(name="survivor", lifetime="detached").remote()
    assert ray_tpu.get(counter.bump.remote()) == 1

    cluster.kill_gcs()
    # Event wait, not a fixed sleep: the reconnect race this exercises
    # (clients dialing mid-outage) only exists once the driver's client
    # has OBSERVED the loss.
    assert cluster.wait_gcs_noticed_down(timeout=10.0)
    cluster.restart_gcs()

    # Raylet + driver reconnect on their next calls; give heartbeats a beat.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            alive = [n for n in cluster.gcs.handle_get_nodes(None)
                     if n["Alive"]]
            if alive:
                break
        except Exception:
            pass
        time.sleep(0.2)
    assert alive, "no node re-registered with the restarted GCS"

    # Live actor handle still works (direct connection was never broken).
    assert ray_tpu.get(counter.bump.remote(), timeout=30) == 2
    # Named lookup resolves from the RESTORED actor table.
    again = ray_tpu.get_actor("survivor")
    assert ray_tpu.get(again.bump.remote(), timeout=30) == 3

    # Fresh task submission end-to-end after failover.
    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(41), timeout=60) == 42


def test_workload_survives_node_churn():
    """Chaos: tasks with retries keep completing while NodeKiller cycles
    worker nodes out from under them."""
    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    try:
        for _ in range(2):
            cluster.add_node(num_cpus=2, resources={"churn": 2})
        cluster.wait_for_nodes()
        cluster.connect()

        @ray_tpu.remote
        def slow_square(x):
            time.sleep(0.2)
            return x * x

        opts = {"resources": {"churn": 1}, "max_retries": 8}
        with NodeKiller(cluster, period_s=1.5, max_kills=3,
                        node_args={"num_cpus": 2,
                                   "resources": {"churn": 2}}) as killer:
            results = ray_tpu.get(
                [slow_square.options(**opts).remote(i) for i in range(24)],
                timeout=180)
        assert results == [i * i for i in range(24)]
        assert killer.kills >= 1, "chaos never fired"
    finally:
        cluster.shutdown()


def test_gcs_reconnect_during_outage_window(persistent_cluster):
    """A client whose call lands INSIDE the kill->restart window must not
    cache the dead endpoint: the reconnect loop keeps re-dialing with
    bounded backoff and the call succeeds once the GCS is back."""
    import threading

    cluster = persistent_cluster
    runtime = ray_tpu._require_runtime()
    cluster.kill_gcs()
    assert cluster.wait_gcs_noticed_down(timeout=10.0)

    result = {}

    def call_during_outage():
        try:
            runtime.gcs.call("kv_put", {"key": b"outage:probe",
                                        "value": b"ok"}, timeout=30)
            result["ok"] = True
        except Exception as e:  # noqa: BLE001
            result["err"] = e

    t = threading.Thread(target=call_during_outage, daemon=True)
    t.start()
    time.sleep(1.0)  # the call is now dialing a dead address
    cluster.restart_gcs()
    t.join(timeout=30)
    assert not t.is_alive(), "call hung past the reconnect deadline"
    assert result.get("ok"), f"call failed: {result.get('err')}"
    assert runtime.gcs.call("kv_get",
                            {"key": b"outage:probe"})["value"] == b"ok"


def test_gcs_kill_during_persist_never_loads_torn_snapshot():
    """Crash the GCS at the worst persistence instants — mid-.tmp-write
    and between write and rename — and prove a restart always loads a
    complete snapshot (fsync + atomic replace), never a torn one."""
    import os as _os

    ray_tpu.shutdown()
    path = os.path.join(tempfile.mkdtemp(), "gcs_tables.bin")
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2},
                      gcs_storage_path=path)
    try:
        cluster.wait_for_nodes()
        cluster.connect()
        actor_cls = ray_tpu.remote(Counter)
        for i in range(3):
            actor_cls.options(name=f"durable-{i}",
                              lifetime="detached").remote()
        # Ensure at least one complete snapshot exists.
        cluster.gcs._persist_tables()
        good = open(path, "rb").read()
        assert good

        # Crash shape 1: killed mid-.tmp-write — a partial .tmp next to a
        # complete snapshot. The restart must ignore (and remove) it.
        with open(path + ".tmp", "wb") as f:
            f.write(good[: len(good) // 2])
        # Crash shape 2: killed between write and rename — simulated by a
        # persist whose os.replace never ran (the .tmp above) while the
        # tables moved on in memory.
        cluster.kill_gcs()
        cluster.restart_gcs()
        assert not _os.path.exists(path + ".tmp")
        # The restored actor table has every named actor of the snapshot.
        restored = {info.name for info in cluster.gcs.actors.values()
                    if info.name}
        assert {f"durable-{i}" for i in range(3)} <= restored, restored

        # Crash shape 3: many kill/restart cycles against the live
        # persist loop (snapshots every gcs_persist_interval_s) with the
        # tables mutating — every restart must load cleanly.
        for cycle in range(3):
            actor_cls.options(name=f"churn-{cycle}",
                              lifetime="detached").remote()
            time.sleep(0.15)  # race the persist loop on purpose
            cluster.kill_gcs()
            cluster.restart_gcs()  # raises if the snapshot were torn
            assert cluster.gcs.actors is not None
    finally:
        cluster.shutdown()


@pytest.mark.slow
def test_lineage_reconstruction_under_node_death_storm():
    """Kill EVERY holder of task results (no drain — crash path), then
    get(): the owner must reconstruct via lineage and the values must be
    byte-correct. Regression for the torn-read bug: a driver polling its
    store mid-pull could attach the raylet's half-written segment (now
    impossible — segments are staged and renamed into place at seal)."""
    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    try:
        for _ in range(2):
            cluster.add_node(num_cpus=2, resources={"churn": 2})
        cluster.wait_for_nodes()
        cluster.connect()

        @ray_tpu.remote
        def make_blob(i):
            import numpy as np

            return np.full((1 << 19,), i, dtype=np.uint8)

        opts = {"resources": {"churn": 0.5}, "max_retries": 4}
        refs = [make_blob.options(**opts).remote(i) for i in range(8)]
        ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=60)
        assert len(ready) == len(refs)
        for victim in [r for r in cluster.raylets if not r.is_head]:
            cluster.crash_node(victim)
        for _ in range(2):
            cluster.add_node(num_cpus=2, resources={"churn": 2})
        vals = ray_tpu.get(refs, timeout=120)
        assert all(int(v[0]) == i and len(v) == (1 << 19)
                   for i, v in enumerate(vals))
    finally:
        cluster.shutdown()


def test_gcs_restart_rekicks_inflight_actor_restart(persistent_cluster):
    """GCS failover re-kick: an actor whose restart was IN FLIGHT when
    the GCS died must not wedge in RESTARTING — the restarted GCS
    reschedules every unresolved actor from its restored tables."""
    cluster = persistent_cluster
    runtime = ray_tpu._require_runtime()

    @ray_tpu.remote(max_restarts=2)
    class Survivor:
        def ping(self):
            import os

            return os.getpid()

    s = Survivor.remote()
    pid1 = ray_tpu.get(s.ping.remote(), timeout=30)
    # Let a persist cycle capture the ALIVE actor.
    cluster.gcs._persist_tables()
    # Crash the worker and the GCS back to back: the restart is (very
    # likely) still in flight when the GCS dies; either way the restored
    # GCS must drive the actor back to ALIVE.
    runtime.raylet.call("chaos_kill_worker",
                        {"draw": 0, "actors_only": True})
    cluster.kill_gcs()
    assert cluster.wait_gcs_noticed_down(timeout=10.0)
    cluster.restart_gcs()
    deadline = time.monotonic() + 60
    pid2 = None
    while time.monotonic() < deadline:
        try:
            pid2 = ray_tpu.get(s.ping.remote(), timeout=5)
            break
        except Exception:  # noqa: BLE001 — restart still converging
            time.sleep(0.3)
    assert pid2 is not None, "actor wedged after GCS failover"
    assert pid2 != pid1
