"""ID bit-layout invariants (reference: src/ray/design_docs/id_specification.md)."""

from ray_tpu.core.ids import ActorID, JobID, ObjectID, TaskID


def test_job_id_roundtrip():
    j = JobID.from_int(7)
    assert j.int_value() == 7
    assert JobID.from_hex(j.hex()) == j


def test_actor_id_embeds_job():
    j = JobID.from_int(3)
    a = ActorID.of(j)
    assert a.job_id() == j


def test_task_id_embeds_actor_and_job():
    j = JobID.from_int(9)
    a = ActorID.of(j)
    t = TaskID.for_actor_task(a)
    assert t.actor_id() == a
    assert t.job_id() == j
    t2 = TaskID.for_task(j)
    assert t2.job_id() == j


def test_object_id_embeds_task():
    j = JobID.from_int(1)
    t = TaskID.for_task(j)
    o = ObjectID.for_return(t, 1)
    assert o.task_id() == t
    assert o.job_id() == j
    assert o.object_index() == 1
    p = ObjectID.for_put(t, 1)
    assert p != o
    assert p.task_id() == t


def test_nil_and_equality():
    n = TaskID.nil()
    assert n.is_nil()
    a = TaskID.for_task(JobID.from_int(1))
    assert a != n
    assert len({a, a}) == 1


def test_pickle_roundtrip():
    import pickle

    t = TaskID.for_task(JobID.from_int(5))
    assert pickle.loads(pickle.dumps(t)) == t
