"""Continuous-batching inference engine: block manager, scheduler, Serve.

Parity target: Orca-style iteration-level scheduling + vLLM-style paged
KV cache. The engine must (a) match the dense KV-decode reference token
for token, (b) never recompile its two step programs, (c) degrade via
preemption instead of OOM, and (d) leak zero blocks across any schedule.
"""

import threading
import time

import pytest

from conftest import assert_compiles_once
from ray_tpu.inference.kv_cache import TRASH_BLOCK, BlockManager


# --------------------------------------------------------------------- #
# Block manager (pure bookkeeping, no jax)
# --------------------------------------------------------------------- #


def test_block_manager_alloc_free():
    bm = BlockManager(num_blocks=9, block_size=4)
    assert bm.capacity == 8 and bm.num_free() == 8
    bm.register("a")
    assert bm.ensure("a", 10)          # 3 blocks
    assert bm.blocks_in_use() == 3
    assert len(bm.block_table("a")) == 3
    assert TRASH_BLOCK not in bm.block_table("a")
    assert bm.ensure("a", 10)          # idempotent
    assert bm.blocks_in_use() == 3
    assert bm.free("a") == 3
    assert bm.blocks_in_use() == 0
    bm.check_consistency()


def test_block_manager_exhaustion_returns_false():
    bm = BlockManager(num_blocks=5, block_size=2)   # 4 allocatable
    bm.register("a")
    bm.register("b")
    assert bm.ensure("a", 6)           # 3 blocks
    assert not bm.ensure("b", 4)       # needs 2, only 1 free
    assert bm.ensure("b", 2)           # 1 block fits
    assert not bm.fits(100)
    bm.free("a")
    assert bm.ensure("b", 8)
    bm.free("b")
    bm.check_consistency()
    assert bm.blocks_in_use() == 0


def test_block_manager_fork_refcounts_and_cow():
    bm = BlockManager(num_blocks=17, block_size=4)
    bm.register("parent")
    assert bm.ensure("parent", 10)     # 3 blocks
    bm.fork("parent", "child")
    assert bm.block_table("child") == bm.block_table("parent")
    assert bm.blocks_in_use() == 3     # shared, not copied
    # Appending to a shared tail must copy-on-write.
    cow = bm.ensure_appendable("child")
    assert cow is not None and cow[1] != -1
    src, dst = cow
    assert bm.block_table("child")[-1] == dst
    assert bm.block_table("parent")[-1] == src
    assert bm.blocks_in_use() == 4
    assert bm.ensure_appendable("child") is None   # now exclusive
    # Freeing the parent keeps the shared prefix alive for the child.
    assert bm.free("parent") == 1      # only the old tail was exclusive
    assert bm.blocks_in_use() == 3
    assert bm.free("child") == 3
    assert bm.blocks_in_use() == 0
    bm.check_consistency()


def test_block_manager_cow_exhaustion_degrades():
    bm = BlockManager(num_blocks=4, block_size=2)   # 3 allocatable
    bm.register("p")
    assert bm.ensure("p", 6)           # all 3 blocks
    bm.fork("p", "c")
    assert bm.ensure_appendable("c") == (bm.block_table("c")[-1], -1)
    bm.free("p")
    bm.free("c")
    bm.check_consistency()


def test_block_manager_randomized_fuzz():
    """Seeded fork/append/free fuzz: any interleaving of COW forks,
    appends, frees and radix-style table adoptions keeps the refcount
    invariants (`check_consistency` after EVERY op) and a full drain
    returns the arena to empty — the zero-leak contract the engine's
    `check_no_leaks` builds on."""
    import random

    rng = random.Random(0x5EED)
    bm = BlockManager(num_blocks=25, block_size=4)
    tokens = {}                        # live seq_id -> token count
    spawned = 0
    for _ in range(600):
        roll = rng.random()
        if roll < 0.35 or not tokens:              # new sequence
            sid = f"s{spawned}"
            spawned += 1
            n = rng.randint(1, 12)
            bm.register(sid)
            if bm.ensure(sid, n):
                tokens[sid] = n
            else:                                  # pool full: back out
                bm.free(sid)
        elif roll < 0.60:                          # append one token
            sid = rng.choice(sorted(tokens))
            cow = bm.ensure_appendable(sid)
            if cow is not None and cow[1] == -1:
                pass                               # COW exhausted: no-op
            elif bm.ensure(sid, tokens[sid] + 1):
                tokens[sid] += 1
        elif roll < 0.75:                          # fork (shared prefix)
            child = f"s{spawned}"
            spawned += 1
            parent = rng.choice(sorted(tokens))
            bm.fork(parent, child)
            tokens[child] = tokens[parent]
        elif roll < 0.85:                          # adopt (radix-style)
            twin = f"s{spawned}"
            spawned += 1
            donor = rng.choice(sorted(tokens))
            bm.register_with_blocks(twin, bm.block_table(donor))
            tokens[twin] = tokens[donor]
        else:                                      # free
            sid = rng.choice(sorted(tokens))
            bm.free(sid)
            del tokens[sid]
        bm.check_consistency()
        assert bm.blocks_in_use() <= bm.capacity
    for sid in sorted(tokens):
        bm.free(sid)
        bm.check_consistency()
    assert bm.blocks_in_use() == 0 and bm.num_seqs() == 0


# --------------------------------------------------------------------- #
# Radix prefix cache (pure bookkeeping, no jax)
# --------------------------------------------------------------------- #


def test_radix_cache_insert_match_split_evict():
    from ray_tpu.inference.kv_cache import RadixPrefixCache

    bm = BlockManager(num_blocks=17, block_size=4)
    cache = RadixPrefixCache(bm)
    bm.register("donor")
    assert bm.ensure("donor", 12)
    table = list(bm.block_table("donor"))
    assert cache.insert(list(range(12)), table) == 3   # 3 novel blocks
    # The donor frees; the cache's synthetic table keeps the KV alive.
    assert bm.free("donor") == 0
    cache.check_consistency()
    assert cache.cached_blocks() == 3 == bm.blocks_in_use()

    # Full-prefix hit returns the donor's physical blocks in order.
    hit, node = cache.match(list(range(12)))
    assert hit == table and node is not None

    # Partial match splits the edge so the returned node covers EXACTLY
    # the matched span (pinning it protects nothing extra).
    hit2, node2 = cache.match(list(range(8)) + [77, 78, 79, 80])
    assert hit2 == table[:2]
    cache.check_consistency()
    cache.pin(node2)

    # Adoption: a reader increfs the cached blocks, frees its own ref.
    bm.register_with_blocks("reader", hit2)
    bm.check_consistency()
    assert bm.free("reader") == 0          # cache still holds them
    assert cache.cached_blocks() == 3

    # Eviction is LRU over UNPINNED leaves: the pinned 2-block prefix
    # survives unbounded pressure; only the unpinned tail leaf goes.
    assert cache.evict_for(1000) == 1
    assert cache.cached_blocks() == 2
    cache.unpin(node2)
    assert cache.evict_for(1000) == 2
    assert cache.cached_blocks() == 0
    cache.check_consistency()
    assert bm.blocks_in_use() == 0
    s = cache.stats()
    assert s["lookups"] == 2 and s["hits"] == 2
    assert s["inserted_blocks"] == 3 and s["evicted_blocks"] == 3


def test_radix_cache_dedupes_branches_and_clears():
    from ray_tpu.inference.kv_cache import RadixPrefixCache

    bm = BlockManager(num_blocks=17, block_size=4)
    cache = RadixPrefixCache(bm)
    bm.register("d1")
    assert bm.ensure("d1", 12)
    t1 = list(bm.block_table("d1"))
    cache.insert(list(range(12)), t1)
    bm.free("d1")

    # Second donor shares the first 8 tokens, diverges in block 3: the
    # shared span dedupes onto the tree's blocks (the donor's duplicates
    # return to the pool when it frees), only the novel block is kept.
    bm.register("d2")
    assert bm.ensure("d2", 12)
    t2 = list(bm.block_table("d2"))
    toks2 = list(range(8)) + [90, 91, 92, 93]
    assert cache.insert(toks2, t2) == 1
    assert bm.free("d2") == 2              # the two duplicated blocks
    cache.check_consistency()
    assert cache.cached_blocks() == 4 == bm.blocks_in_use()

    # Both branches resolve to their own tails over the shared prefix.
    hit1, _ = cache.match(list(range(12)))
    hit2, _ = cache.match(toks2)
    assert hit1 == t1
    assert hit2 == t1[:2] + t2[2:]
    # Partial blocks never match (alphabet is FULL blocks only).
    hit3, node3 = cache.match(list(range(3)))
    assert hit3 == [] and node3 is None

    assert cache.clear() == 4
    cache.check_consistency()
    assert cache.cached_blocks() == 0 and bm.blocks_in_use() == 0


# --------------------------------------------------------------------- #
# Engine
# --------------------------------------------------------------------- #


@pytest.fixture(scope="module")
def tiny_llama():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.llama import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(seq=256)
    model = Llama(cfg)
    params = jax.jit(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)))()
    return model, params


def _reference_generate(model, params, prompt, n):
    """Dense KV-cache greedy loop — the engine must match it exactly."""
    import jax.numpy as jnp

    from ray_tpu.models.llama import Llama, make_cache

    cache = make_cache(model.config, 1, 256)
    ids = jnp.asarray([prompt], jnp.int32)
    logits, cache = model.apply(params, ids, cache,
                                jnp.zeros(1, jnp.int32),
                                method=Llama.decode)
    toks = [int(jnp.argmax(logits[0, -1]))]
    pos = len(prompt)
    while len(toks) < n:
        logits, cache = model.apply(params,
                                    jnp.asarray([[toks[-1]]], jnp.int32),
                                    cache, jnp.asarray([pos], jnp.int32),
                                    method=Llama.decode)
        toks.append(int(jnp.argmax(logits[0, -1])))
        pos += 1
    return toks


def _make_engine(tiny_llama, **overrides):
    from ray_tpu.inference import EngineConfig, InferenceEngine

    model, params = tiny_llama
    draft = {k: overrides.pop(k) for k in ("draft_model", "draft_params")
             if k in overrides}
    kwargs = dict(batch_slots=3, block_size=4, num_blocks=64,
                  max_blocks_per_seq=16, prefill_chunk=8)
    kwargs.update(overrides)
    return InferenceEngine(EngineConfig(**kwargs), model=model,
                           params=params, **draft)


def test_engine_matches_reference_and_compiles_once(tiny_llama):
    model, params = tiny_llama
    engine = _make_engine(tiny_llama)
    reqs = [engine.add_request([1 + i, 2 + i, 3 + i, 4 + i],
                               max_new_tokens=4 + i) for i in range(5)]
    engine.run_until_idle()
    for req in reqs:
        assert req.state == "FINISHED"
        ref = _reference_generate(model, params, req.prompt,
                                  req.max_new_tokens)
        assert req.generated == ref, req.request_id
    stats = engine.stats()
    # The whole run — mixed admissions, exits, chunked prefill — used
    # exactly one prefill program and one decode program.
    assert_compiles_once(stats, "prefill_compiles", "decode_compiles")
    engine.check_no_leaks()


def test_chunked_prefill_interleaves_with_decode(tiny_llama):
    """A long prompt prefilling in chunks must not stall an already-
    decoding sequence's token emission."""
    events = []
    engine = _make_engine(tiny_llama, batch_slots=2, prefill_chunk=4)
    short = engine.add_request(
        [1, 2, 3], max_new_tokens=12,
        on_token=lambda r, t: events.append(("short", t)),
        request_id="short")
    # Let the short request finish prefill and start decoding.
    while short.state != "DECODE":
        engine.step()
    long = engine.add_request(
        list(range(1, 33)), max_new_tokens=4,      # 8 prefill chunks
        on_token=lambda r, t: events.append(("long", t)),
        request_id="long")
    engine.run_until_idle()
    assert short.state == "FINISHED" and long.state == "FINISHED"
    first_long = next(i for i, (who, _) in enumerate(events)
                      if who == "long")
    short_before_long = sum(1 for who, _ in events[:first_long]
                            if who == "short")
    # Several short-request tokens were emitted while the long prompt was
    # still prefilling (with chunk=4 its prefill spans 8 engine steps).
    assert short_before_long >= 3, events
    engine.check_no_leaks()


def test_preemption_recovers_and_leaks_nothing(tiny_llama):
    model, params = tiny_llama
    # Arena so small that two growing sequences cannot both stay
    # resident: the later arrival must be preempted, recomputed, and
    # still finish with exactly its solo output.
    engine = _make_engine(tiny_llama, batch_slots=2, block_size=2,
                          num_blocks=9, max_blocks_per_seq=8,
                          prefill_chunk=4)
    a = engine.add_request([1, 2, 3], max_new_tokens=10, request_id="a")
    b = engine.add_request([4, 5, 6], max_new_tokens=10, request_id="b")
    engine.run_until_idle()
    assert a.state == b.state == "FINISHED"
    stats = engine.stats()
    assert stats["preemptions"] >= 1
    # Priority: the older request is never the victim.
    assert a.preemptions == 0 and b.preemptions >= 1
    assert a.generated == _reference_generate(model, params, a.prompt, 10)
    assert b.generated == _reference_generate(model, params, b.prompt, 10)
    # The victim's blocks were freed and re-acquired; nothing leaked —
    # the only remaining references are the radix cache's donations,
    # and dropping those drains the arena to empty.
    engine.check_no_leaks()
    engine.drop_prefix_cache()
    engine.check_no_leaks()
    assert engine.stats()["kv"]["blocks_in_use"] == 0
    assert_compiles_once(stats, "decode_compiles")  # preemption didn't recompile


def test_engine_rejects_oversized_request(tiny_llama):
    engine = _make_engine(tiny_llama, block_size=2, num_blocks=8,
                          max_blocks_per_seq=4)
    with pytest.raises(ValueError, match="token slots"):
        engine.add_request(list(range(20)), max_new_tokens=20)
    engine.check_no_leaks()


def test_engine_eager_smoke(tiny_llama):
    """Interpreter-mode (no jit) smoke: the tier-1 fast path through the
    whole scheduler without paying any XLA compile."""
    engine = _make_engine(tiny_llama, use_jit=False, batch_slots=2,
                          prefill_chunk=4)
    req = engine.add_request([1, 2, 3], max_new_tokens=3)
    engine.run_until_idle()
    assert req.state == "FINISHED" and len(req.generated) == 3
    engine.check_no_leaks()


def test_engine_loop_threaded_streaming(tiny_llama):
    from ray_tpu.inference import EngineLoop

    engine = _make_engine(tiny_llama)
    loop = EngineLoop(engine)
    try:
        done = threading.Event()
        tokens = []
        req = loop.submit([1, 2, 3], 6,
                          on_token=lambda r, t: tokens.append(t),
                          on_finish=lambda r: done.set())
        assert done.wait(60)
        assert tokens == req.generated and len(tokens) == 6
    finally:
        loop.stop()
    engine.check_no_leaks()


def test_cancel_releases_slot_and_blocks(tiny_llama):
    """An abandoned request (client disconnect) must free its slot and
    blocks immediately so queued traffic takes its place."""
    engine = _make_engine(tiny_llama, batch_slots=1)
    done = []
    a = engine.add_request([1, 2, 3], max_new_tokens=50,
                           request_id="abandoned")
    b = engine.add_request([4, 5], max_new_tokens=3, request_id="live",
                           on_finish=lambda r: done.append(r.request_id))
    for _ in range(3):
        engine.step()                  # a holds the only slot, b queued
    assert a.state == "DECODE" and b.state == "WAITING"
    assert engine.cancel("abandoned")
    assert a.state == "FAILED" and a.error == "cancelled"
    assert not engine.cancel("abandoned")    # idempotent
    engine.run_until_idle()
    assert b.state == "FINISHED" and done == ["live"]
    engine.check_no_leaks()
    # A finished request's id may be reused (not leaked in the live set).
    engine.add_request([1], 1, request_id="abandoned")
    engine.run_until_idle()
    engine.check_no_leaks()


def test_duplicate_request_id_rejected_at_submit(tiny_llama):
    engine = _make_engine(tiny_llama)
    engine.add_request([1, 2], max_new_tokens=4, request_id="dup")
    with pytest.raises(ValueError, match="already live"):
        engine.add_request([3, 4], max_new_tokens=4, request_id="dup")
    engine.run_until_idle()
    engine.check_no_leaks()


def test_fail_all_and_submit_after_stop(tiny_llama):
    """The loop's circuit breaker: fail_all must resolve every in-flight
    and queued request (callers see the error, never a hung future), and
    a stopped loop refuses new work instead of stranding it."""
    from ray_tpu.inference import EngineLoop

    engine = _make_engine(tiny_llama, batch_slots=2)
    finished = []
    reqs = [engine.add_request([1 + i], max_new_tokens=50,
                               on_finish=lambda r: finished.append(r),
                               request_id=f"f{i}") for i in range(4)]
    engine.step()                       # two scheduled, two waiting
    assert engine.fail_all("injected failure") == 4
    assert len(finished) == 4
    assert all(r.state == "FAILED" and r.error == "injected failure"
               for r in reqs)
    engine.check_no_leaks()

    # The engine recovers: fail_all rebuilt the (donated) arena, so new
    # requests complete normally afterwards.
    recovered = engine.add_request([7, 8], max_new_tokens=3)
    engine.run_until_idle()
    assert recovered.state == "FINISHED" and len(recovered.generated) == 3
    engine.check_no_leaks()

    loop = EngineLoop(engine)
    loop.stop()
    with pytest.raises(RuntimeError, match="stopped"):
        loop.submit([1], 2)


def test_static_gang_holds_results_until_drain(tiny_llama):
    """The @serve.batch-shaped baseline: a short request admitted with a
    long one sees its tokens only when the whole gang drains."""
    engine = _make_engine(tiny_llama, batch_slots=2, scheduling="static")
    r_short = engine.add_request([1, 2], max_new_tokens=2,
                                 request_id="short")
    r_long = engine.add_request([3, 4], max_new_tokens=16,
                                request_id="long")
    r_next = engine.add_request([5], max_new_tokens=2, request_id="next")
    engine.run_until_idle()
    assert r_short.state == r_long.state == r_next.state == "FINISHED"
    assert abs(r_short.first_token_at - r_long.finished_at) < 0.5
    assert r_next.first_token_at >= r_long.finished_at   # second gang
    engine.check_no_leaks()


# --------------------------------------------------------------------- #
# Radix prefix cache through the engine
# --------------------------------------------------------------------- #


def test_prefix_cache_hit_skips_prefill_no_new_programs(tiny_llama):
    """Acceptance: a repeated prompt adopts its cached blocks (skipping
    their prefill), produces bit-identical output, and compiles ZERO new
    XLA programs on the cached path."""
    model, params = tiny_llama
    engine = _make_engine(tiny_llama)              # block_size=4
    prompt = list(range(1, 10))                    # 9 tokens
    ref = _reference_generate(model, params, prompt, 6)
    a = engine.add_request(prompt, max_new_tokens=6)
    engine.run_until_idle()
    assert a.generated == ref and a.cached_tokens == 0
    s0 = engine.stats()["prefix_cache"]
    assert s0["cached_blocks"] >= 2 and s0["hits"] == 0

    b = engine.add_request(prompt, max_new_tokens=6)
    engine.run_until_idle()
    assert b.generated == ref
    # Match is block-aligned and capped one token short of the stream:
    # 8 of the 9 prompt tokens ride the cache, one still prefills.
    assert b.cached_tokens == 8
    st = engine.stats()
    assert st["prefix_cache"]["hits"] == 1
    assert st["prefix_cache"]["hit_tokens"] == 8
    assert 0.0 < st["prefix_cache"]["hit_rate"] <= 1.0
    assert_compiles_once(st, "prefill_compiles", "decode_compiles")
    engine.check_no_leaks()
    engine.drop_prefix_cache()
    engine.check_no_leaks()
    assert engine.stats()["kv"]["blocks_in_use"] == 0


def test_prefix_cache_evicts_under_arena_pressure(tiny_llama):
    """A cold cached prefix yields its blocks to live traffic: the big
    request fits by evicting cache leaves, not by preempting/failing."""
    engine = _make_engine(tiny_llama, use_jit=False, batch_slots=1,
                          num_blocks=13, block_size=4,
                          max_blocks_per_seq=12, prefill_chunk=8)
    engine.add_request(list(range(1, 9)), max_new_tokens=4)
    engine.run_until_idle()
    assert engine.stats()["prefix_cache"]["cached_blocks"] >= 2
    big = engine.add_request(list(range(100, 140)), max_new_tokens=6)
    engine.run_until_idle()
    assert big.state == "FINISHED"
    st = engine.stats()
    assert st["prefix_cache"]["evicted_blocks"] >= 1
    assert st["preemptions"] == 0
    engine.check_no_leaks()


def test_prefix_cache_live_sequence_pins_its_path(tiny_llama):
    """A decoding sequence pins its matched node: even direct maximal
    eviction pressure must not reclaim blocks its KV reads through."""
    model, params = tiny_llama
    engine = _make_engine(tiny_llama, use_jit=False)
    prompt = list(range(1, 10))
    engine.add_request(prompt, max_new_tokens=3)
    engine.run_until_idle()                        # primes the cache
    slow = engine.add_request(prompt, max_new_tokens=12)
    while slow.state != "DECODE":
        engine.step()
    assert slow.cached_tokens == 8
    assert engine.stats()["prefix_cache"]["pinned_nodes"] == 1
    engine._prefix.evict_for(10_000)               # maximal pressure
    # The pinned 2-block path survived; only unpinned tails could go.
    assert engine.stats()["prefix_cache"]["cached_blocks"] >= 2
    engine.run_until_idle()
    assert slow.generated == _reference_generate(model, params, prompt, 12)
    assert engine.stats()["prefix_cache"]["pinned_nodes"] == 0
    engine.check_no_leaks()


def test_fail_all_clears_prefix_cache_and_recovers(tiny_llama):
    """The arena rebuild invalidates every cached block's contents, so
    fail_all must drop the tree with it — and the engine re-primes."""
    engine = _make_engine(tiny_llama, use_jit=False)
    a = engine.add_request(list(range(1, 9)), max_new_tokens=4)
    engine.run_until_idle()
    assert engine.stats()["prefix_cache"]["cached_blocks"] > 0
    engine.fail_all("injected")
    st = engine.stats()
    assert st["prefix_cache"]["cached_blocks"] == 0
    assert st["kv"]["blocks_in_use"] == 0
    b = engine.add_request(list(range(1, 9)), max_new_tokens=4)
    engine.run_until_idle()
    assert b.generated == a.generated
    assert engine.stats()["prefix_cache"]["cached_blocks"] > 0
    engine.check_no_leaks()


# --------------------------------------------------------------------- #
# Speculative decoding
# --------------------------------------------------------------------- #


def test_spec_decode_lossless_and_compiles_once(tiny_llama):
    """Greedy spec decode is LOSSLESS: with the default truncated-target
    draft the output is bit-identical to the dense reference, and the
    three spec programs (draft prefill / propose / verify) each compile
    exactly once across mixed admissions."""
    model, params = tiny_llama
    engine = _make_engine(tiny_llama, spec_decode_draft_len=3)
    reqs = [engine.add_request([1 + i, 2 + i, 3 + i], max_new_tokens=6)
            for i in range(3)]
    engine.run_until_idle()
    for r in reqs:
        assert r.generated == _reference_generate(model, params,
                                                  r.prompt, 6), r.request_id
    sd = engine.stats()["spec_decode"]
    assert sd["draft_len"] == 3 and sd["rounds"] > 0
    assert sum(sd["accepted_hist"]) == sd["rounds"]
    assert_compiles_once(sd, "draft_prefill_compiles", "propose_compiles",
                         "verify_compiles")
    assert_compiles_once(engine.stats(), "prefill_compiles")
    engine.check_no_leaks()
    engine.drop_prefix_cache()
    assert engine.stats()["kv"]["blocks_in_use"] == 0


def test_spec_decode_target_draft_accepts_everything(tiny_llama):
    """Upper bound: with the target itself as draft every proposal is
    accepted, so n tokens cost ceil(n / (k+1)) verify rounds."""
    model, params = tiny_llama
    engine = _make_engine(tiny_llama, use_jit=False,
                          spec_decode_draft_len=3,
                          draft_model=model, draft_params=params)
    r = engine.add_request([1, 2, 3, 4], max_new_tokens=8)
    engine.run_until_idle()
    assert r.generated == _reference_generate(model, params, [1, 2, 3, 4], 8)
    sd = engine.stats()["spec_decode"]
    assert sd["accept_rate"] == 1.0
    assert sd["rounds"] == 2                       # 8 tokens, k+1 = 4 each
    assert sd["accepted_hist"][3] == 2
    engine.check_no_leaks()


@pytest.mark.slow  # ~15s eager decode; gate.sh runs the full suite
def test_spec_decode_preemption_rolls_back_without_leaks(tiny_llama):
    """Rejected drafts and preempted rows under block pressure: the
    block tables roll back cleanly (no leaked blocks) and the recomputed
    output stays bit-identical to the reference."""
    model, params = tiny_llama
    engine = _make_engine(tiny_llama, use_jit=False,
                          spec_decode_draft_len=2, batch_slots=2,
                          block_size=2, num_blocks=9,
                          max_blocks_per_seq=8, prefill_chunk=4)
    a = engine.add_request([1, 2, 3], max_new_tokens=10, request_id="a")
    b = engine.add_request([4, 5, 6], max_new_tokens=10, request_id="b")
    engine.run_until_idle()
    assert a.state == b.state == "FINISHED"
    assert engine.stats()["preemptions"] >= 1
    assert a.generated == _reference_generate(model, params, a.prompt, 10)
    assert b.generated == _reference_generate(model, params, b.prompt, 10)
    engine.check_no_leaks()
    engine.drop_prefix_cache()
    assert engine.stats()["kv"]["blocks_in_use"] == 0


# --------------------------------------------------------------------- #
# SLO classes
# --------------------------------------------------------------------- #


def test_slo_interactive_admitted_before_earlier_batch(tiny_llama):
    """Queue order is (class, arrival): a later interactive arrival
    takes the next free slot ahead of a queued batch-class request."""
    engine = _make_engine(tiny_llama, use_jit=False, batch_slots=1)
    hold = engine.add_request([1, 2], max_new_tokens=6, slo_class="batch")
    while hold.state != "DECODE":
        engine.step()
    bat = engine.add_request([3, 4], max_new_tokens=3, slo_class="batch")
    inter = engine.add_request([5, 6], max_new_tokens=3,
                               slo_class="interactive")
    assert engine.stats()["slo"] == {"reserved_slots": 0,
                                     "waiting_interactive": 1,
                                     "waiting_batch": 1}
    engine.run_until_idle()
    assert inter.first_token_at < bat.first_token_at
    engine.check_no_leaks()
    with pytest.raises(ValueError, match="slo_class"):
        engine.add_request([1], 1, slo_class="bulk")


def test_slo_reserved_slots_hold_headroom_for_interactive(tiny_llama):
    """With reserved headroom, batch-class admissions never take the
    last slot(s) — an interactive arrival lands immediately."""
    engine = _make_engine(tiny_llama, use_jit=False, batch_slots=2,
                          slo_interactive_reserved_slots=1)
    b1 = engine.add_request([1, 2], max_new_tokens=8, slo_class="batch")
    b2 = engine.add_request([3, 4], max_new_tokens=8, slo_class="batch")
    for _ in range(4):
        engine.step()
    assert b1.state in ("PREFILL", "DECODE") and b2.state == "WAITING"
    i1 = engine.add_request([5, 6], max_new_tokens=2,
                            slo_class="interactive")
    engine.run_until_idle()
    assert all(r.state == "FINISHED" for r in (b1, b2, i1))
    assert i1.first_token_at < b2.first_token_at
    engine.check_no_leaks()


def test_slo_preemption_prefers_batch_victim(tiny_llama):
    """Under block pressure the victim is the batch-class sequence even
    though it arrived FIRST (class outranks age), and both requests
    still finish with reference-exact output."""
    model, params = tiny_llama
    engine = _make_engine(tiny_llama, use_jit=False, batch_slots=2,
                          block_size=2, num_blocks=9,
                          max_blocks_per_seq=8, prefill_chunk=4)
    bat = engine.add_request([1, 2, 3], max_new_tokens=10,
                             slo_class="batch")
    inter = engine.add_request([4, 5, 6], max_new_tokens=10,
                               slo_class="interactive")
    engine.run_until_idle()
    assert engine.stats()["preemptions"] >= 1
    assert inter.preemptions == 0 and bat.preemptions >= 1
    assert inter.generated == _reference_generate(model, params,
                                                  inter.prompt, 10)
    assert bat.generated == _reference_generate(model, params,
                                                bat.prompt, 10)
    engine.check_no_leaks()


# --------------------------------------------------------------------- #
# Serve integration
# --------------------------------------------------------------------- #


def test_llm_server_generate_and_stream_through_serve(ray_start_regular):
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.inference import LLMServer

    handle = serve.run(LLMServer.options(num_replicas=1).bind(
        "tiny", 128, 8,
        engine_config={"batch_slots": 2, "block_size": 8,
                       "num_blocks": 32, "max_blocks_per_seq": 8,
                       "prefill_chunk": 8}))
    try:
        out = ray_tpu.get(handle.remote(
            {"ids": [1, 2, 3], "max_new_tokens": 5}), timeout=180)
        assert out["ids"][:3] == [1, 2, 3] and len(out["ids"]) == 8

        # Token streaming through replica/handle: one event per token as
        # produced, then the completion event.
        events = list(handle.options(stream=True).stream.remote(
            {"ids": [1, 2, 3], "max_new_tokens": 5}))
        tokens = [e["token"] for e in events if "token" in e]
        assert len(tokens) == 5
        assert events[-1]["done"] and events[-1]["ids"] == out["ids"]

        # Engine metrics ride the replica stats for the autoscaler.
        metrics = ray_tpu.get(handle.metrics.remote(None), timeout=60)
        assert metrics["requests_finished"] >= 2
        assert_compiles_once(metrics, "decode_compiles")
        # Idle arena holds only the prefix cache's donated blocks.
        assert (metrics["kv"]["blocks_in_use"]
                == metrics["prefix_cache"]["cached_blocks"])
        assert "queue_depth" in metrics and "tokens_per_sec" in metrics
    finally:
        serve.shutdown()


def test_llm_server_streams_over_http(ray_start_regular):
    import json
    import urllib.request

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.inference import LLMServer

    serve.run(LLMServer.options(num_replicas=1).bind(
        "tiny", 128, 6,
        engine_config={"batch_slots": 2, "block_size": 8,
                       "num_blocks": 32, "max_blocks_per_seq": 8,
                       "prefill_chunk": 8}))
    try:
        port = serve.http_port()
        # "stream": true switches __call__ to the token stream; items
        # arrive as chunked JSON lines through the proxy.
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/LLMServer",
            data=json.dumps({"ids": [1, 2, 3], "max_new_tokens": 4,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            lines = [json.loads(line) for line in resp.read().splitlines()
                     if line.strip()]
        tokens = [e["token"] for e in lines if "token" in e]
        assert len(tokens) == 4, lines
        assert lines[-1]["done"] and len(lines[-1]["ids"]) == 7

        # Unary HTTP round-trip still works next to streaming.
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/LLMServer",
            data=json.dumps({"ids": [1, 2],
                             "max_new_tokens": 3}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            body = json.loads(resp.read())
        assert len(body["result"]["ids"]) == 5
    finally:
        serve.shutdown()


# --------------------------------------------------------------------- #
# Continuous vs static under Poisson load (bench-backed; slow)
# --------------------------------------------------------------------- #


@pytest.mark.slow
def test_continuous_beats_static_under_poisson_load(tiny_llama):
    """Acceptance: iteration-level scheduling beats gang batching on
    aggregate tokens/s AND p99 TTFT under mixed-length Poisson arrivals,
    with zero leaked blocks and zero decode recompiles. ~30s of decode
    loops: excluded from the tier-1 budget, exercised via bench.py."""
    import bench

    model, params = tiny_llama
    cont = bench._inference_poisson_run("continuous", quick=True,
                                        model=model, params=params)
    stat = bench._inference_poisson_run("static", quick=True,
                                        model=model, params=params)
    assert cont["leaked_blocks"] == 0 and stat["leaked_blocks"] == 0
    assert cont["decode_recompiles"] == 0
    assert cont["tokens_per_sec"] > stat["tokens_per_sec"], (cont, stat)
    assert cont["ttft_p99_ms"] < stat["ttft_p99_ms"], (cont, stat)
