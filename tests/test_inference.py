"""Continuous-batching inference engine: block manager, scheduler, Serve.

Parity target: Orca-style iteration-level scheduling + vLLM-style paged
KV cache. The engine must (a) match the dense KV-decode reference token
for token, (b) never recompile its two step programs, (c) degrade via
preemption instead of OOM, and (d) leak zero blocks across any schedule.
"""

import threading
import time

import pytest

from ray_tpu.inference.kv_cache import TRASH_BLOCK, BlockManager


# --------------------------------------------------------------------- #
# Block manager (pure bookkeeping, no jax)
# --------------------------------------------------------------------- #


def test_block_manager_alloc_free():
    bm = BlockManager(num_blocks=9, block_size=4)
    assert bm.capacity == 8 and bm.num_free() == 8
    bm.register("a")
    assert bm.ensure("a", 10)          # 3 blocks
    assert bm.blocks_in_use() == 3
    assert len(bm.block_table("a")) == 3
    assert TRASH_BLOCK not in bm.block_table("a")
    assert bm.ensure("a", 10)          # idempotent
    assert bm.blocks_in_use() == 3
    assert bm.free("a") == 3
    assert bm.blocks_in_use() == 0
    bm.check_consistency()


def test_block_manager_exhaustion_returns_false():
    bm = BlockManager(num_blocks=5, block_size=2)   # 4 allocatable
    bm.register("a")
    bm.register("b")
    assert bm.ensure("a", 6)           # 3 blocks
    assert not bm.ensure("b", 4)       # needs 2, only 1 free
    assert bm.ensure("b", 2)           # 1 block fits
    assert not bm.fits(100)
    bm.free("a")
    assert bm.ensure("b", 8)
    bm.free("b")
    bm.check_consistency()
    assert bm.blocks_in_use() == 0


def test_block_manager_fork_refcounts_and_cow():
    bm = BlockManager(num_blocks=17, block_size=4)
    bm.register("parent")
    assert bm.ensure("parent", 10)     # 3 blocks
    bm.fork("parent", "child")
    assert bm.block_table("child") == bm.block_table("parent")
    assert bm.blocks_in_use() == 3     # shared, not copied
    # Appending to a shared tail must copy-on-write.
    cow = bm.ensure_appendable("child")
    assert cow is not None and cow[1] != -1
    src, dst = cow
    assert bm.block_table("child")[-1] == dst
    assert bm.block_table("parent")[-1] == src
    assert bm.blocks_in_use() == 4
    assert bm.ensure_appendable("child") is None   # now exclusive
    # Freeing the parent keeps the shared prefix alive for the child.
    assert bm.free("parent") == 1      # only the old tail was exclusive
    assert bm.blocks_in_use() == 3
    assert bm.free("child") == 3
    assert bm.blocks_in_use() == 0
    bm.check_consistency()


def test_block_manager_cow_exhaustion_degrades():
    bm = BlockManager(num_blocks=4, block_size=2)   # 3 allocatable
    bm.register("p")
    assert bm.ensure("p", 6)           # all 3 blocks
    bm.fork("p", "c")
    assert bm.ensure_appendable("c") == (bm.block_table("c")[-1], -1)
    bm.free("p")
    bm.free("c")
    bm.check_consistency()


# --------------------------------------------------------------------- #
# Engine
# --------------------------------------------------------------------- #


@pytest.fixture(scope="module")
def tiny_llama():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.llama import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(seq=256)
    model = Llama(cfg)
    params = jax.jit(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)))()
    return model, params


def _reference_generate(model, params, prompt, n):
    """Dense KV-cache greedy loop — the engine must match it exactly."""
    import jax.numpy as jnp

    from ray_tpu.models.llama import Llama, make_cache

    cache = make_cache(model.config, 1, 256)
    ids = jnp.asarray([prompt], jnp.int32)
    logits, cache = model.apply(params, ids, cache,
                                jnp.zeros(1, jnp.int32),
                                method=Llama.decode)
    toks = [int(jnp.argmax(logits[0, -1]))]
    pos = len(prompt)
    while len(toks) < n:
        logits, cache = model.apply(params,
                                    jnp.asarray([[toks[-1]]], jnp.int32),
                                    cache, jnp.asarray([pos], jnp.int32),
                                    method=Llama.decode)
        toks.append(int(jnp.argmax(logits[0, -1])))
        pos += 1
    return toks


def _make_engine(tiny_llama, **overrides):
    from ray_tpu.inference import EngineConfig, InferenceEngine

    model, params = tiny_llama
    kwargs = dict(batch_slots=3, block_size=4, num_blocks=64,
                  max_blocks_per_seq=16, prefill_chunk=8)
    kwargs.update(overrides)
    return InferenceEngine(EngineConfig(**kwargs), model=model,
                           params=params)


def test_engine_matches_reference_and_compiles_once(tiny_llama):
    model, params = tiny_llama
    engine = _make_engine(tiny_llama)
    reqs = [engine.add_request([1 + i, 2 + i, 3 + i, 4 + i],
                               max_new_tokens=4 + i) for i in range(5)]
    engine.run_until_idle()
    for req in reqs:
        assert req.state == "FINISHED"
        ref = _reference_generate(model, params, req.prompt,
                                  req.max_new_tokens)
        assert req.generated == ref, req.request_id
    stats = engine.stats()
    # The whole run — mixed admissions, exits, chunked prefill — used
    # exactly one prefill program and one decode program.
    assert stats["prefill_compiles"] == 1, stats
    assert stats["decode_compiles"] == 1, stats
    engine.check_no_leaks()


def test_chunked_prefill_interleaves_with_decode(tiny_llama):
    """A long prompt prefilling in chunks must not stall an already-
    decoding sequence's token emission."""
    events = []
    engine = _make_engine(tiny_llama, batch_slots=2, prefill_chunk=4)
    short = engine.add_request(
        [1, 2, 3], max_new_tokens=12,
        on_token=lambda r, t: events.append(("short", t)),
        request_id="short")
    # Let the short request finish prefill and start decoding.
    while short.state != "DECODE":
        engine.step()
    long = engine.add_request(
        list(range(1, 33)), max_new_tokens=4,      # 8 prefill chunks
        on_token=lambda r, t: events.append(("long", t)),
        request_id="long")
    engine.run_until_idle()
    assert short.state == "FINISHED" and long.state == "FINISHED"
    first_long = next(i for i, (who, _) in enumerate(events)
                      if who == "long")
    short_before_long = sum(1 for who, _ in events[:first_long]
                            if who == "short")
    # Several short-request tokens were emitted while the long prompt was
    # still prefilling (with chunk=4 its prefill spans 8 engine steps).
    assert short_before_long >= 3, events
    engine.check_no_leaks()


def test_preemption_recovers_and_leaks_nothing(tiny_llama):
    model, params = tiny_llama
    # Arena so small that two growing sequences cannot both stay
    # resident: the later arrival must be preempted, recomputed, and
    # still finish with exactly its solo output.
    engine = _make_engine(tiny_llama, batch_slots=2, block_size=2,
                          num_blocks=9, max_blocks_per_seq=8,
                          prefill_chunk=4)
    a = engine.add_request([1, 2, 3], max_new_tokens=10, request_id="a")
    b = engine.add_request([4, 5, 6], max_new_tokens=10, request_id="b")
    engine.run_until_idle()
    assert a.state == b.state == "FINISHED"
    stats = engine.stats()
    assert stats["preemptions"] >= 1
    # Priority: the older request is never the victim.
    assert a.preemptions == 0 and b.preemptions >= 1
    assert a.generated == _reference_generate(model, params, a.prompt, 10)
    assert b.generated == _reference_generate(model, params, b.prompt, 10)
    # The victim's blocks were freed and re-acquired; nothing leaked.
    engine.check_no_leaks()
    assert stats["kv"]["blocks_in_use"] == 0
    assert stats["decode_compiles"] == 1   # preemption didn't recompile


def test_engine_rejects_oversized_request(tiny_llama):
    engine = _make_engine(tiny_llama, block_size=2, num_blocks=8,
                          max_blocks_per_seq=4)
    with pytest.raises(ValueError, match="token slots"):
        engine.add_request(list(range(20)), max_new_tokens=20)
    engine.check_no_leaks()


def test_engine_eager_smoke(tiny_llama):
    """Interpreter-mode (no jit) smoke: the tier-1 fast path through the
    whole scheduler without paying any XLA compile."""
    engine = _make_engine(tiny_llama, use_jit=False, batch_slots=2,
                          prefill_chunk=4)
    req = engine.add_request([1, 2, 3], max_new_tokens=3)
    engine.run_until_idle()
    assert req.state == "FINISHED" and len(req.generated) == 3
    engine.check_no_leaks()


def test_engine_loop_threaded_streaming(tiny_llama):
    from ray_tpu.inference import EngineLoop

    engine = _make_engine(tiny_llama)
    loop = EngineLoop(engine)
    try:
        done = threading.Event()
        tokens = []
        req = loop.submit([1, 2, 3], 6,
                          on_token=lambda r, t: tokens.append(t),
                          on_finish=lambda r: done.set())
        assert done.wait(60)
        assert tokens == req.generated and len(tokens) == 6
    finally:
        loop.stop()
    engine.check_no_leaks()


def test_cancel_releases_slot_and_blocks(tiny_llama):
    """An abandoned request (client disconnect) must free its slot and
    blocks immediately so queued traffic takes its place."""
    engine = _make_engine(tiny_llama, batch_slots=1)
    done = []
    a = engine.add_request([1, 2, 3], max_new_tokens=50,
                           request_id="abandoned")
    b = engine.add_request([4, 5], max_new_tokens=3, request_id="live",
                           on_finish=lambda r: done.append(r.request_id))
    for _ in range(3):
        engine.step()                  # a holds the only slot, b queued
    assert a.state == "DECODE" and b.state == "WAITING"
    assert engine.cancel("abandoned")
    assert a.state == "FAILED" and a.error == "cancelled"
    assert not engine.cancel("abandoned")    # idempotent
    engine.run_until_idle()
    assert b.state == "FINISHED" and done == ["live"]
    engine.check_no_leaks()
    # A finished request's id may be reused (not leaked in the live set).
    engine.add_request([1], 1, request_id="abandoned")
    engine.run_until_idle()
    engine.check_no_leaks()


def test_duplicate_request_id_rejected_at_submit(tiny_llama):
    engine = _make_engine(tiny_llama)
    engine.add_request([1, 2], max_new_tokens=4, request_id="dup")
    with pytest.raises(ValueError, match="already live"):
        engine.add_request([3, 4], max_new_tokens=4, request_id="dup")
    engine.run_until_idle()
    engine.check_no_leaks()


def test_fail_all_and_submit_after_stop(tiny_llama):
    """The loop's circuit breaker: fail_all must resolve every in-flight
    and queued request (callers see the error, never a hung future), and
    a stopped loop refuses new work instead of stranding it."""
    from ray_tpu.inference import EngineLoop

    engine = _make_engine(tiny_llama, batch_slots=2)
    finished = []
    reqs = [engine.add_request([1 + i], max_new_tokens=50,
                               on_finish=lambda r: finished.append(r),
                               request_id=f"f{i}") for i in range(4)]
    engine.step()                       # two scheduled, two waiting
    assert engine.fail_all("injected failure") == 4
    assert len(finished) == 4
    assert all(r.state == "FAILED" and r.error == "injected failure"
               for r in reqs)
    engine.check_no_leaks()

    # The engine recovers: fail_all rebuilt the (donated) arena, so new
    # requests complete normally afterwards.
    recovered = engine.add_request([7, 8], max_new_tokens=3)
    engine.run_until_idle()
    assert recovered.state == "FINISHED" and len(recovered.generated) == 3
    engine.check_no_leaks()

    loop = EngineLoop(engine)
    loop.stop()
    with pytest.raises(RuntimeError, match="stopped"):
        loop.submit([1], 2)


def test_static_gang_holds_results_until_drain(tiny_llama):
    """The @serve.batch-shaped baseline: a short request admitted with a
    long one sees its tokens only when the whole gang drains."""
    engine = _make_engine(tiny_llama, batch_slots=2, scheduling="static")
    r_short = engine.add_request([1, 2], max_new_tokens=2,
                                 request_id="short")
    r_long = engine.add_request([3, 4], max_new_tokens=16,
                                request_id="long")
    r_next = engine.add_request([5], max_new_tokens=2, request_id="next")
    engine.run_until_idle()
    assert r_short.state == r_long.state == r_next.state == "FINISHED"
    assert abs(r_short.first_token_at - r_long.finished_at) < 0.5
    assert r_next.first_token_at >= r_long.finished_at   # second gang
    engine.check_no_leaks()


# --------------------------------------------------------------------- #
# Serve integration
# --------------------------------------------------------------------- #


def test_llm_server_generate_and_stream_through_serve(ray_start_regular):
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.inference import LLMServer

    handle = serve.run(LLMServer.options(num_replicas=1).bind(
        "tiny", 128, 8,
        engine_config={"batch_slots": 2, "block_size": 8,
                       "num_blocks": 32, "max_blocks_per_seq": 8,
                       "prefill_chunk": 8}))
    try:
        out = ray_tpu.get(handle.remote(
            {"ids": [1, 2, 3], "max_new_tokens": 5}), timeout=180)
        assert out["ids"][:3] == [1, 2, 3] and len(out["ids"]) == 8

        # Token streaming through replica/handle: one event per token as
        # produced, then the completion event.
        events = list(handle.options(stream=True).stream.remote(
            {"ids": [1, 2, 3], "max_new_tokens": 5}))
        tokens = [e["token"] for e in events if "token" in e]
        assert len(tokens) == 5
        assert events[-1]["done"] and events[-1]["ids"] == out["ids"]

        # Engine metrics ride the replica stats for the autoscaler.
        metrics = ray_tpu.get(handle.metrics.remote(None), timeout=60)
        assert metrics["requests_finished"] >= 2
        assert metrics["decode_compiles"] == 1
        assert metrics["kv"]["blocks_in_use"] == 0
        assert "queue_depth" in metrics and "tokens_per_sec" in metrics
    finally:
        serve.shutdown()


def test_llm_server_streams_over_http(ray_start_regular):
    import json
    import urllib.request

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.inference import LLMServer

    serve.run(LLMServer.options(num_replicas=1).bind(
        "tiny", 128, 6,
        engine_config={"batch_slots": 2, "block_size": 8,
                       "num_blocks": 32, "max_blocks_per_seq": 8,
                       "prefill_chunk": 8}))
    try:
        port = serve.http_port()
        # "stream": true switches __call__ to the token stream; items
        # arrive as chunked JSON lines through the proxy.
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/LLMServer",
            data=json.dumps({"ids": [1, 2, 3], "max_new_tokens": 4,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            lines = [json.loads(line) for line in resp.read().splitlines()
                     if line.strip()]
        tokens = [e["token"] for e in lines if "token" in e]
        assert len(tokens) == 4, lines
        assert lines[-1]["done"] and len(lines[-1]["ids"]) == 7

        # Unary HTTP round-trip still works next to streaming.
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/LLMServer",
            data=json.dumps({"ids": [1, 2],
                             "max_new_tokens": 3}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            body = json.loads(resp.read())
        assert len(body["result"]["ids"]) == 5
    finally:
        serve.shutdown()


# --------------------------------------------------------------------- #
# Continuous vs static under Poisson load (bench-backed; slow)
# --------------------------------------------------------------------- #


@pytest.mark.slow
def test_continuous_beats_static_under_poisson_load(tiny_llama):
    """Acceptance: iteration-level scheduling beats gang batching on
    aggregate tokens/s AND p99 TTFT under mixed-length Poisson arrivals,
    with zero leaked blocks and zero decode recompiles. ~30s of decode
    loops: excluded from the tier-1 budget, exercised via bench.py."""
    import bench

    model, params = tiny_llama
    cont = bench._inference_poisson_run("continuous", quick=True,
                                        model=model, params=params)
    stat = bench._inference_poisson_run("static", quick=True,
                                        model=model, params=params)
    assert cont["leaked_blocks"] == 0 and stat["leaked_blocks"] == 0
    assert cont["decode_recompiles"] == 0
    assert cont["tokens_per_sec"] > stat["tokens_per_sec"], (cont, stat)
    assert cont["ttft_p99_ms"] < stat["ttft_p99_ms"], (cont, stat)
