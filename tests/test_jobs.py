"""Job tier (docs/JOBS.md): submission plane, runtime envs, jobs-as-
tenants, and job-scoped isolation/cleanup.

Mirrors the reference's `python/ray/tests/test_job_manager.py` +
runtime_env job tests, adapted to the agent-based submission plane
(GCS job table -> per-node agent -> driver subprocess).
"""

import os
import sys
import time

import pytest

import ray_tpu
from ray_tpu.job_submission import JobStatus, JobSubmissionClient


def _wait_terminal(client, sid, timeout=120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        status = client.get_job_status(sid)
        if status in JobStatus.TERMINAL:
            return status
        time.sleep(0.25)
    return client.get_job_status(sid)


def _client():
    return JobSubmissionClient(ray_tpu._global_runtime.gcs.address)


# --------------------------------------------------------------------------- #
# Submission plane: runtime envs and tenants ride the job record
# --------------------------------------------------------------------------- #


def test_submit_with_runtime_env_and_tenant(ray_start_regular):
    client = _client()
    sid = client.submit_job(
        entrypoint=(
            f"{sys.executable} -c \""
            "import os, ray_tpu; ray_tpu.init()\n"
            "print('MARKER=' + os.environ.get('JOB_MARKER', 'missing'))\n"
            "@ray_tpu.remote\n"
            "def probe():\n"
            "    return os.environ.get('JOB_MARKER', 'missing')\n"
            "print('TASK_SAW=' + ray_tpu.get(probe.remote()))\n"
            "ray_tpu.shutdown()\""),
        runtime_env={"env_vars": {"JOB_MARKER": "tenant-e2e"}},
        tenant={"name": "batch-team", "tier": "gold"},
        metadata={"owner": "jobs-test"})
    status = _wait_terminal(client, sid)
    logs = client.get_job_logs(sid)
    assert status == JobStatus.SUCCEEDED, f"status={status} logs={logs[-800:]}"
    # env_vars reach the driver process AND its workers (task-level
    # inheritance of the job runtime_env).
    assert "MARKER=tenant-e2e" in logs
    assert "TASK_SAW=tenant-e2e" in logs
    info = client.get_job_info(sid)
    assert info.status == JobStatus.SUCCEEDED
    assert info.tenant == "batch-team"
    assert info.runtime_env.get("env_vars") == {"JOB_MARKER": "tenant-e2e"}
    assert info.driver_job_id, "driver job never linked to the submission"
    assert info.node_id, "job record never recorded its agent node"
    client.close()


def test_submit_bad_tenant_rejected(ray_start_regular):
    client = _client()
    with pytest.raises(RuntimeError, match="tenant"):
        client.submit_job(entrypoint="true",
                          tenant={"name": "x", "tier": "platinum"})
    client.close()


def test_concurrent_jobs_with_distinct_envs(ray_start_regular):
    """Acceptance: N concurrent jobs with different runtime envs share
    one cluster; each sees only its own env (worker isolation by job)."""
    client = _client()
    sids = []
    for i in range(3):
        sids.append(client.submit_job(
            entrypoint=(
                f"{sys.executable} -c \""
                "import os, ray_tpu; ray_tpu.init()\n"
                "@ray_tpu.remote\n"
                "def who():\n"
                "    return os.environ.get('JOB_COLOR', '?')\n"
                "got = ray_tpu.get([who.remote() for _ in range(4)])\n"
                "print('COLORS=' + ','.join(sorted(set(got))))\n"
                "ray_tpu.shutdown()\""),
            runtime_env={"env_vars": {"JOB_COLOR": f"color-{i}"}}))
    for i, sid in enumerate(sids):
        status = _wait_terminal(client, sid)
        logs = client.get_job_logs(sid)
        assert status == JobStatus.SUCCEEDED, \
            f"job {i} status={status} logs={logs[-800:]}"
        assert f"COLORS=color-{i}" in logs, logs[-800:]
    client.close()


# --------------------------------------------------------------------------- #
# Job-scoped isolation: KV purge, worker reclamation
# --------------------------------------------------------------------------- #


def test_job_scoped_kv_purged_on_finish(ray_start_regular):
    client = _client()
    sid = client.submit_job(
        entrypoint=(
            f"{sys.executable} -c \""
            "import ray_tpu; ray_tpu.init()\n"
            "ray_tpu.kv_put('state', b'job-private')\n"
            "print('KV=' + ray_tpu.kv_get('state').decode())\n"
            "ray_tpu.shutdown()\""))
    status = _wait_terminal(client, sid)
    logs = client.get_job_logs(sid)
    assert status == JobStatus.SUCCEEDED, logs[-800:]
    assert "KV=job-private" in logs
    job_hex = client.get_job_info(sid).driver_job_id
    gcs = ray_tpu._global_runtime.gcs
    # The whole job:<hex>: namespace died with the job.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        resp = gcs.call("kv_get", {"namespace": f"job:{job_hex}:default",
                                   "key": b"state"})
        if resp.get("value") is None:
            break
        time.sleep(0.2)
    assert resp.get("value") is None, "job-scoped KV outlived its job"
    client.close()


def test_interactive_kv_is_job_scoped(ray_start_regular):
    ray_tpu.kv_put("k1", b"v1")
    assert ray_tpu.kv_get("k1") == b"v1"
    assert ray_tpu.kv_get("missing") is None
    ray_tpu.kv_del("k1")
    assert ray_tpu.kv_get("k1") is None
    # Scoping: the raw GCS key lives under this job's namespace.
    rt = ray_tpu._global_runtime
    ray_tpu.kv_put("k2", b"v2", namespace="ns")
    raw = rt.gcs.call("kv_get", {
        "namespace": f"job:{rt.job_id.hex()}:ns", "key": b"k2"})
    assert raw.get("value") == b"v2"


def test_job_workers_reclaimed_after_finish(ray_start_regular):
    """A finished job's workers (leased by its job-tagged env) retire:
    no orphan idle workers pin the pool for an env no task can want."""
    client = _client()
    sid = client.submit_job(
        entrypoint=(
            f"{sys.executable} -c \""
            "import ray_tpu; ray_tpu.init()\n"
            "@ray_tpu.remote\n"
            "def f(i):\n"
            "    return i\n"
            "print(sum(ray_tpu.get([f.remote(i) for i in range(8)])))\n"
            "ray_tpu.shutdown()\""))
    assert _wait_terminal(client, sid) == JobStatus.SUCCEEDED, \
        client.get_job_logs(sid)[-800:]
    job_hex = client.get_job_info(sid).driver_job_id
    raylet = ray_tpu._global_node.raylet  # in-process head node
    deadline = time.monotonic() + 20
    leftovers = None
    while time.monotonic() < deadline:
        with raylet.pool._lock:
            leftovers = [h for h in raylet.pool._workers.values()
                         if h.state not in ("dead",)
                         and h.granted_env.get("RAY_TPU_JOB_ID") == job_hex]
        if not leftovers:
            break
        time.sleep(0.5)
    assert not leftovers, \
        f"{len(leftovers)} workers survived their job's finish"
    client.close()


# --------------------------------------------------------------------------- #
# Detached actors: first-class lifetime, cross-job name resolution
# --------------------------------------------------------------------------- #


def test_detached_actor_survives_job(ray_start_regular):
    client = _client()
    sid = client.submit_job(
        entrypoint=(
            f"{sys.executable} -c \""
            "import ray_tpu; ray_tpu.init()\n"
            "@ray_tpu.remote\n"
            "class Keeper:\n"
            "    def __init__(self):\n"
            "        self.v = 0\n"
            "    def bump(self):\n"
            "        self.v += 1\n"
            "        return self.v\n"
            "d = Keeper.options(name='jobs-keeper', "
            "lifetime='detached').remote()\n"
            "e = Keeper.options(name='jobs-ephemeral').remote()\n"
            "print('BUMP=', ray_tpu.get(d.bump.remote()))\n"
            "print('EPH=', ray_tpu.get(e.bump.remote()))\n"
            "ray_tpu.shutdown()\""))
    status = _wait_terminal(client, sid)
    assert status == JobStatus.SUCCEEDED, client.get_job_logs(sid)[-800:]
    # Cross-job name resolution: this (interactive) driver is a different
    # job, yet the detached actor resolves by name and kept its state.
    handle = ray_tpu.get_actor("jobs-keeper")
    assert ray_tpu.get(handle.bump.remote(), timeout=30) == 2
    # The non-detached actor died with its owning job.
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        try:
            ray_tpu.get_actor("jobs-ephemeral")
        except ValueError:
            break
        time.sleep(0.25)
    with pytest.raises(ValueError):
        ray_tpu.get_actor("jobs-ephemeral")
    ray_tpu.kill(handle)
    client.close()


# --------------------------------------------------------------------------- #
# Working dir: prepared client-side, materialized on the agent node
# --------------------------------------------------------------------------- #


def test_working_dir_job(ray_start_regular, tmp_path):
    (tmp_path / "jobdata.txt").write_text("payload-42\n")
    (tmp_path / "jobmod.py").write_text(
        "def answer():\n    return open('jobdata.txt').read().strip()\n")
    client = _client()
    sid = client.submit_job(
        entrypoint=(
            f"{sys.executable} -c \""
            "import jobmod\n"
            "print('DATA=' + jobmod.answer())\""),
        runtime_env={"working_dir": str(tmp_path)})
    status = _wait_terminal(client, sid)
    logs = client.get_job_logs(sid)
    assert status == JobStatus.SUCCEEDED, logs[-800:]
    # The driver ran INSIDE the materialized working_dir (cwd on
    # sys.path + relative file reads both resolve), which the client
    # uploaded as a content-addressed zip — the record carries the URI,
    # never the client-local path.
    assert "DATA=payload-42" in logs
    assert client.get_job_info(sid).runtime_env["working_dir"].startswith(
        "kv://runtime_env/")
    client.close()


# --------------------------------------------------------------------------- #
# GCS failover: the job table is checkpointed state
# --------------------------------------------------------------------------- #


def test_job_table_survives_gcs_restart():
    import tempfile

    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    path = os.path.join(tempfile.mkdtemp(), "gcs_tables.bin")
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2},
                      gcs_storage_path=path)
    cluster.wait_for_nodes()
    cluster.connect()
    try:
        client = JobSubmissionClient(ray_tpu._global_runtime.gcs.address)
        done = client.submit_job(
            entrypoint=f"{sys.executable} -c \"print('done-job')\"",
            metadata={"k": "v"})
        assert _wait_terminal(client, done) == JobStatus.SUCCEEDED
        client.close()
        # Force a snapshot cycle to include the terminal record, then
        # fail the GCS over.
        cluster.gcs._persist_tables()
        cluster.restart_gcs()
        client = JobSubmissionClient(ray_tpu._global_runtime.gcs.address)
        deadline = time.monotonic() + 30
        info = None
        while time.monotonic() < deadline:
            try:
                info = client.get_job_info(done)
                break
            except (ValueError, OSError):
                time.sleep(0.5)
        assert info is not None, "job record lost across GCS restart"
        assert info.status == JobStatus.SUCCEEDED
        assert info.metadata == {"k": "v"}
        client.close()
    finally:
        cluster.shutdown()


# --------------------------------------------------------------------------- #
# JobAdmission: stride fairness + rate quotas (unit)
# --------------------------------------------------------------------------- #


def test_job_admission_stride_fairness():
    from ray_tpu.jobs.tenancy import JobAdmission

    adm = JobAdmission()
    adm.register("gold", {"name": "g", "tier": "gold"})     # weight 8
    adm.register("bronze", {"name": "b", "tier": "bronze"})  # weight 1
    grants = {"gold": 0, "bronze": 0}
    for _ in range(90):
        winner = adm.order(["gold", "bronze"])[0]
        assert adm.admit(winner) == 0.0
        grants[winner] += 1
    # ~8:1 split (stride scheduling): 80 vs 10 exactly for these weights.
    assert grants["gold"] == 80, grants
    assert grants["bronze"] == 10, grants


def test_job_admission_rate_quota_and_refund():
    from ray_tpu.jobs.tenancy import JobAdmission

    adm = JobAdmission()
    adm.register("metered", {"name": "m", "rps_limit": 1.0, "burst": 2.0})
    now = 100.0
    assert adm.admit("metered", now=now) == 0.0
    assert adm.admit("metered", now=now) == 0.0
    wait = adm.admit("metered", now=now)  # burst exhausted
    assert wait > 0.0
    # Refund restores the token: the next admit at the same instant works.
    adm.refund("metered")
    assert adm.admit("metered", now=now) == 0.0
    # Unknown jobs admit with defaults (lazy entry), and unregister drops
    # the entry outright.
    assert adm.admit("anon") == 0.0
    adm.unregister("anon")
    adm.unregister("metered")
    assert adm.snapshot() == {}


def test_env_hash_stability():
    from ray_tpu.core.runtime_env import env_hash

    assert env_hash(None) == ""
    assert env_hash({}) == ""
    a = env_hash({"env_vars": {"A": "1", "B": "2"}, "preimports": ["x", "y"]})
    b = env_hash({"preimports": ["y", "x"], "env_vars": {"B": "2", "A": "1"}})
    assert a == b, "env_hash must canonicalize ordering"
    assert a != env_hash({"env_vars": {"A": "1"}})
    assert len(a) == 16
