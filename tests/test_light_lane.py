"""Serve light lane + control-plane fast-path serialization.

Covers the r5 perf plumbing: the C-pickle fast path in serialize()/
dumps_ctrl() (with the __main__ by-reference fallback), the DEFERRED
deferred-reply RPC mechanism (rpc.py), router reserve()/release()
admission accounting, and the proxy's actor_call_light lane end to end
over HTTP (tests/test_serve_asgi.py covers the ASGI shapes; this file
covers the transport).
"""

import json
import threading
import urllib.request

import pytest


# --------------------------------------------------------------------- #
# serialize() fast path
# --------------------------------------------------------------------- #


class MainishClass:
    """Defined in a test module (importable on workers via PYTHONPATH), so
    plain pickle-by-reference works for it; the __main__ fallback is
    exercised below by faking the module name."""

    def __init__(self, v):
        self.v = v


def test_serialize_plain_data_roundtrip():
    from ray_tpu.core import serialization

    for value in [1, "x", b"raw", {"a": [1, 2, (3, None)]}, [1.5, True]]:
        blob = serialization.serialize_to_bytes(value)
        assert serialization.deserialize(blob) == value


def test_serialize_main_class_falls_back_by_value():
    """A class claiming __module__ == '__main__' must be captured by value
    (cloudpickle), not by reference — by-reference would dump fine and
    fail to resolve on the worker."""
    from ray_tpu.core import serialization

    cls = type("DriverLocal", (), {"__module__": "__main__", "v": 7})
    blob = serialization.serialize_to_bytes(cls)
    # The blob must NOT contain a bare by-reference main lookup: by-value
    # capture embeds cloudpickle machinery instead.
    out = serialization.deserialize(blob)
    assert out.v == 7
    # And an instance inside a container:
    inst = cls()
    blob = serialization.serialize_to_bytes({"obj": inst})
    assert serialization.deserialize(blob)["obj"].v == 7


def test_serialize_string_mentioning_main_still_roundtrips():
    from ray_tpu.core import serialization

    value = {"note": "__main__ appears in this perfectly plain string"}
    assert serialization.deserialize(
        serialization.serialize_to_bytes(value)) == value


def test_dumps_ctrl_closure_falls_back():
    from ray_tpu.core import serialization

    x = 41

    def closure():
        return x + 1

    blob = serialization.dumps_ctrl({"fn": closure})
    assert serialization.loads(blob)["fn"]() == 42


def test_serialize_oob_buffers_survive_fallback():
    """The failed fast attempt must not leak its out-of-band buffers into
    the cloudpickle retry (oob.clear())."""
    import numpy as np

    from ray_tpu.core import serialization

    cls = type("MainArr", (), {"__module__": "__main__"})
    holder = cls()
    holder.arr = np.arange(1024, dtype=np.float64)
    blob = serialization.serialize_to_bytes({"h": holder})
    out = serialization.deserialize(blob)
    assert out["h"].arr.sum() == holder.arr.sum()


# --------------------------------------------------------------------- #
# DEFERRED deferred replies
# --------------------------------------------------------------------- #


def test_rpc_deferred_reply():
    from ray_tpu.core.rpc import DEFERRED, RpcClient, RpcServer

    server = RpcServer(name="deferred-test")
    done = threading.Event()

    def slow_echo(conn, data):
        mid = conn.current_msg_id

        def later():
            conn.reply(mid, "slow_echo", {"r": data["x"] * 2})
            done.set()

        threading.Timer(0.05, later).start()
        return DEFERRED

    server.register("slow_echo", slow_echo)
    server.register("fast", lambda conn, data: {"ok": True})
    server.start()
    try:
        client = RpcClient(server.address, name="deferred-client")
        # Deferred call resolves with the later reply; an interleaved
        # normal call on the same connection is unaffected (out-of-order
        # response matching by msg id).
        results = {}
        ev = threading.Event()

        def cb(env, payload):
            from ray_tpu.core import serialization

            results["deferred"] = serialization.loads(bytes(payload))
            ev.set()

        client.call_async("slow_echo", {"x": 21}, cb)
        assert client.call("fast", {}, timeout=5)["ok"] is True
        assert ev.wait(5)
        assert results["deferred"]["r"] == 42
        assert done.wait(5)
        client.close()
    finally:
        server.stop()


# --------------------------------------------------------------------- #
# Router admission accounting
# --------------------------------------------------------------------- #


def test_router_reserve_release_balance():
    from ray_tpu.serve.router import Router

    router = Router.__new__(Router)  # no controller: drive the table directly
    router._lock = threading.Condition()
    router._waiters = 0
    router._version = 0
    router._inflight = {}
    router._outstanding = {}
    router._started = True
    router._table = {"d": {"max_concurrent_queries": 2,
                           "route_prefix": "/d",
                           "replicas": [("r1", object()), ("r2", object())]}}

    got = [router.reserve("d") for _ in range(5)]
    taken = [g for g in got if g is not None]
    # 2 replicas x limit 2 = 4 slots; the 5th reserve must be refused.
    assert len(taken) == 4 and got[-1] is None
    assert sorted(router._inflight.values()) == [2, 2]
    for rid, _ in taken:
        router.release(rid)
    assert all(v == 0 for v in router._inflight.values())
    # Saturated then released: reserve works again.
    assert router.reserve("d") is not None


def test_router_release_notifies_waiters():
    from ray_tpu.serve.router import Router

    router = Router.__new__(Router)
    router._lock = threading.Condition()
    router._waiters = 0
    router._version = 0
    router._inflight = {}
    router._outstanding = {}
    router._started = True
    router._table = {"d": {"max_concurrent_queries": 1,
                           "route_prefix": "/d",
                           "replicas": [("r1", object())]}}
    rid, _ = router.reserve("d")

    woke = threading.Event()

    def waiter():
        with router._lock:
            while router._reserve_locked(router._table["d"]) is None:
                router._waiters += 1
                try:
                    if not router._lock.wait(timeout=5):
                        return
                finally:
                    router._waiters -= 1
        woke.set()

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    import time

    time.sleep(0.2)
    router.release(rid)
    assert woke.wait(5), "release() with a parked waiter must notify"


# --------------------------------------------------------------------- #
# Light lane end to end
# --------------------------------------------------------------------- #


@pytest.mark.usefixtures("ray_start_regular")
def test_serve_http_light_lane_end_to_end():
    import ray_tpu
    from ray_tpu import serve

    @serve.deployment(num_replicas=1, max_concurrent_queries=8)
    class Echo:
        def __call__(self, payload):
            return {"echo": payload}

    serve.run(Echo.bind())
    try:
        port = serve.http_port()
        url = f"http://127.0.0.1:{port}/Echo"
        for i in range(10):
            req = urllib.request.Request(
                url, data=json.dumps({"i": i}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert json.loads(resp.read()) == {"result": {"echo": {"i": i}}}
        # Admission slots must be balanced after the burst: the proxy's
        # router lives in the proxy actor, so assert via behavior — the
        # deployment still serves after > max_concurrent_queries requests
        # (a leaked slot per request would starve it by request 9).
        req = urllib.request.Request(
            url, data=b'{"last": true}',
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert json.loads(resp.read())["result"]["echo"] == {"last": True}
    finally:
        serve.shutdown()
