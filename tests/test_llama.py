"""Llama model family: forward, sharded training, KV-cache decode, serving.

Parity target: the second model family next to GPT-2, with the
decode-against-cache inference shape a Serve LLM deployment needs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import assert_compiles_once
from ray_tpu.models.llama import (
    Llama,
    LlamaConfig,
    flops_per_token,
    make_cache,
)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny(seq=32)
    model = Llama(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (2, 10), 0,
                             cfg.vocab_size, dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    return cfg, model, ids, params


def test_forward_shape_and_gqa(tiny_model):
    cfg, model, ids, params = tiny_model
    logits = model.apply(params, ids)
    assert logits.shape == (2, 10, cfg.vocab_size)
    assert cfg.n_head % cfg.n_kv_head == 0 and cfg.n_kv_head < cfg.n_head
    assert flops_per_token(cfg, 32) > 0


def test_train_step_reduces_loss(tiny_model):
    import optax

    from ray_tpu.models.gpt2 import make_train_step

    cfg, model, ids, params = tiny_model
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    step = make_train_step(model, opt, donate=False)
    batch = {"input_ids": ids, "labels": ids}
    _, _, first = step(params, opt_state, batch)
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, batch)
    assert float(loss) < float(first)


def test_decode_matches_full_forward(tiny_model):
    cfg, model, ids, params = tiny_model
    full = model.apply(params, ids)
    # Prefill in one shot.
    cache = make_cache(cfg, 2, 32)
    pf, cache = model.apply(params, ids, cache, jnp.zeros(2, jnp.int32),
                            method=Llama.decode)
    np.testing.assert_allclose(np.asarray(pf, np.float32),
                               np.asarray(full, np.float32),
                               atol=0.06, rtol=0.05)
    # Token-by-token decode agrees position-wise.
    cache2 = make_cache(cfg, 2, 32)
    for t in range(ids.shape[1]):
        lg, cache2 = model.apply(params, ids[:, t:t + 1], cache2,
                                 jnp.full((2,), t, jnp.int32),
                                 method=Llama.decode)
        np.testing.assert_allclose(np.asarray(lg[:, 0], np.float32),
                                   np.asarray(full[:, t], np.float32),
                                   atol=0.06, rtol=0.05)


def test_decode_per_row_positions(tiny_model):
    """Rows at different lengths decode against their own offsets."""
    cfg, model, ids, params = tiny_model
    full = model.apply(params, ids)
    cache = make_cache(cfg, 2, 32)
    model_apply = lambda tok, c, pos: model.apply(  # noqa: E731
        params, tok, c, pos, method=Llama.decode)
    # Prefill row 0 with 4 tokens, row 1 with 7 (padded batch prefill).
    _, cache = model_apply(ids, cache, jnp.zeros(2, jnp.int32))
    # Next-token decode at row-specific positions 4 and 7.
    lg, cache = model_apply(
        jnp.stack([ids[0, 4:5], ids[1, 7:8]]), cache,
        jnp.asarray([4, 7], jnp.int32))
    np.testing.assert_allclose(np.asarray(lg[0, 0], np.float32),
                               np.asarray(full[0, 4], np.float32),
                               atol=0.06, rtol=0.05)
    np.testing.assert_allclose(np.asarray(lg[1, 0], np.float32),
                               np.asarray(full[1, 7], np.float32),
                               atol=0.06, rtol=0.05)


def test_sharded_init_on_mesh(tiny_model):
    from ray_tpu.models.gpt2 import init_sharded
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh

    cfg, model, ids, _ = tiny_model
    mesh = build_mesh(MeshSpec({"dp": 2, "fsdp": 2, "tp": 2}))
    params = init_sharded(model, mesh, (2, 16))
    n = sum(x.size for x in jax.tree.leaves(params))
    assert n > 0


def test_llama_sampler_through_serve(ray_start_regular):
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.examples import LlamaSampler

    handle = serve.run(LlamaSampler.options(num_replicas=1).bind(
        "tiny", 64, 8))
    try:
        out = ray_tpu.get(handle.remote(
            {"ids": [1, 2, 3], "max_new_tokens": 5}), timeout=180)
        assert out["ids"][:3] == [1, 2, 3] and len(out["ids"]) == 8
        outs = ray_tpu.get([handle.remote(
            {"ids": [5 + i], "max_new_tokens": 4}) for i in range(6)],
            timeout=180)
        for i, o in enumerate(outs):
            assert o["ids"][0] == 5 + i and len(o["ids"]) == 5
    finally:
        serve.shutdown()


@pytest.mark.parametrize("n_kv_head", [1, 2, 4])
def test_decode_parity_and_compile_once(n_kv_head):
    """Satellite: prefill + N single-token decode steps must match the
    full causal forward across GQA ratios (MQA=1, grouped=2, MHA=4), and
    the jitted decode step must compile exactly once across steps."""
    cfg = LlamaConfig(vocab_size=128, n_positions=64, n_embd=64,
                      n_layer=2, n_head=4, n_kv_head=n_kv_head,
                      intermediate=96, use_flash=False)
    model = Llama(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0,
                             cfg.vocab_size, dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(1), ids)
    full = model.apply(params, ids)

    decode_step = jax.jit(lambda p, tok, cache, pos: model.apply(
        p, tok, cache, pos, method=Llama.decode))
    cache = make_cache(cfg, 2, 64)
    # Prefill the first 4 tokens in one shot, then decode one at a time.
    prefill = jax.jit(lambda p, tok, cache, pos: model.apply(
        p, tok, cache, pos, method=Llama.decode))
    _, cache = prefill(params, ids[:, :4], cache, jnp.zeros(2, jnp.int32))
    for t in range(4, ids.shape[1]):
        lg, cache = decode_step(params, ids[:, t:t + 1], cache,
                                jnp.full((2,), t, jnp.int32))
        np.testing.assert_allclose(np.asarray(lg[:, 0], np.float32),
                                   np.asarray(full[:, t], np.float32),
                                   atol=0.06, rtol=0.05)
    # Shape-stable decode: one XLA program served every step.
    assert_compiles_once(decode_step)


def test_paged_decode_matches_dense(tiny_model):
    """Paged-arena decode (block tables, scattered physical blocks) must
    agree with the dense per-row cache path token for token."""
    from ray_tpu.models.llama import make_paged_arena

    cfg, model, ids, params = tiny_model
    full = model.apply(params, ids)
    arena = make_paged_arena(cfg, 16, 4)
    # Deliberately shuffled physical blocks: logical order comes from the
    # table, not arena layout.
    # (unreached tail entries are trash-padded with 0, as the engine's
    # block tables are)
    bt = jnp.asarray([[3, 1, 6, 2, 5, 4, 9, 0],
                      [7, 13, 8, 12, 11, 14, 15, 0]], jnp.int32)
    wm1 = jnp.ones((2, 1), bool)
    for t in range(ids.shape[1]):
        lg, arena = model.apply(params, ids[:, t:t + 1], arena, bt,
                                jnp.full((2,), t, jnp.int32), wm1,
                                method=Llama.decode_paged)
        np.testing.assert_allclose(np.asarray(lg[:, 0], np.float32),
                                   np.asarray(full[:, t], np.float32),
                                   atol=0.06, rtol=0.05)
