"""Memory monitor + OOM worker killing.

Reference behavior: `src/ray/common/memory_monitor.h:52` (threshold
polling, cgroup-aware) and `src/ray/raylet/worker_killing_policy.h:34`
(retriable tasks first, actors spared). Pressure is injected via the
monitor's usage_fn so the test exercises the kill/retry path without
exhausting the host.
"""

import os
import time

import pytest


def test_system_memory_sane():
    from ray_tpu.core.memory_monitor import process_rss, system_memory

    used, total = system_memory()
    assert 0 < used <= total
    rss = process_rss(os.getpid())
    assert rss > 10 * 1024 * 1024  # a Python interpreter is >10MB


def _pressure_monitor(raylet, flag):
    from ray_tpu.core.memory_monitor import MemoryMonitor

    return MemoryMonitor(
        raylet, refresh_ms=50, threshold=0.9,
        usage_fn=lambda: (95, 100) if flag["on"] else (10, 100))


def test_oom_kills_retriable_task_and_it_retries(tmp_path):
    """A memory-hog retriable task is killed under pressure and retried;
    a stateful actor on the same node survives untouched."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 4})
    cluster.connect()
    flag = {"on": False}
    mm = _pressure_monitor(cluster.raylets[0], flag)
    mm.start()
    try:
        marker = str(tmp_path / "attempt")

        @ray_tpu.remote(max_retries=2)
        def hog(path):
            first = not os.path.exists(path)
            with open(path, "a") as f:
                f.write("x")
            if first:
                time.sleep(60)   # parked until the OOM killer fires
            return "recovered"

        @ray_tpu.remote
        class Keeper:
            def __init__(self):
                self.state = 41

            def bump(self):
                self.state += 1
                return self.state

        keeper = Keeper.remote()
        assert ray_tpu.get(keeper.bump.remote()) == 42

        ref = hog.remote(marker)
        deadline = time.monotonic() + 30
        while not os.path.exists(marker):
            assert time.monotonic() < deadline, "task never started"
            time.sleep(0.05)
        time.sleep(0.2)
        flag["on"] = True
        assert ray_tpu.get(ref, timeout=60) == "recovered"
        flag["on"] = False
        assert mm.kills >= 1
        # The actor kept its state: it was never considered a victim.
        assert ray_tpu.get(keeper.bump.remote()) == 43
    finally:
        mm.stop()
        cluster.shutdown()


def test_oom_error_type_for_non_retriable(tmp_path):
    """A non-retriable classic-path task killed by the monitor fails with
    a typed OutOfMemoryError explaining the usage."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.exceptions import OutOfMemoryError
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 4})
    cluster.connect()
    flag = {"on": False}
    mm = _pressure_monitor(cluster.raylets[0], flag)
    mm.start()
    try:
        marker = str(tmp_path / "started")
        node_id = cluster.raylets[0].node_id

        @ray_tpu.remote(max_retries=0)
        def hog(path):
            open(path, "w").write("x")
            time.sleep(60)

        # A scheduling strategy forces the classic raylet path (the
        # direct transport reports crashes generically).
        ref = hog.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=node_id, soft=True)).remote(marker)
        deadline = time.monotonic() + 30
        while not os.path.exists(marker):
            assert time.monotonic() < deadline, "task never started"
            time.sleep(0.05)
        time.sleep(0.2)
        flag["on"] = True
        with pytest.raises(OutOfMemoryError, match="memory usage"):
            ray_tpu.get(ref, timeout=60)
    finally:
        mm.stop()
        cluster.shutdown()


def test_monitor_starts_from_system_config():
    """The declared flag actually configures something now: raylets
    started with memory_monitor_refresh_ms > 0 run a monitor."""
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2,
                 _system_config={"memory_monitor_refresh_ms": 100,
                                 "memory_usage_threshold": 0.99})
    try:
        node = ray_tpu._global_node
        mm = getattr(node.raylet, "memory_monitor", None)
        assert mm is not None
        assert mm._period_s == pytest.approx(0.1)
        assert mm._threshold == 0.99
    finally:
        ray_tpu.shutdown()


def test_victim_policy_prefers_newest_retriable():
    from ray_tpu.core.memory_monitor import MemoryMonitor

    class FakeSpec:
        def __init__(self, max_retries, actor_creation=False):
            self.max_retries = max_retries
            self.actor_creation = actor_creation
            self.name = "t"

    class FakeHandle:
        def __init__(self, state="busy", spec=None, is_actor=False,
                     started=0.0):
            self.state = state
            self.current_task = spec
            self.is_actor = is_actor
            self.proc = object()
            self.oom_kill_reason = None
            self.task_started = started
            self.last_idle = started
            self.pid = 1

    class FakePool:
        import threading

        _lock = threading.Lock()

        def __init__(self, workers):
            self._workers = {i: w for i, w in enumerate(workers)}

    class FakeRaylet:
        def __init__(self, workers):
            self.pool = FakePool(workers)

    old_retriable = FakeHandle(spec=FakeSpec(2), started=1.0)
    new_retriable = FakeHandle(spec=FakeSpec(2), started=2.0)
    newest_nonretriable = FakeHandle(spec=FakeSpec(0), started=9.0)
    actor = FakeHandle(is_actor=True, started=99.0)
    idle = FakeHandle(state="idle")
    mm = MemoryMonitor(FakeRaylet([old_retriable, new_retriable,
                                   newest_nonretriable, actor, idle]),
                       refresh_ms=1000, threshold=0.95)
    victim, spec, retriable = mm._pick_victim()
    assert victim is new_retriable and retriable
    assert spec is new_retriable.current_task

    # No retriable: newest non-retriable; actors never.
    mm2 = MemoryMonitor(FakeRaylet([newest_nonretriable, actor]),
                        refresh_ms=1000, threshold=0.95)
    victim, spec, retriable = mm2._pick_victim()
    assert victim is newest_nonretriable and not retriable

    mm3 = MemoryMonitor(FakeRaylet([actor, idle]), refresh_ms=1000,
                        threshold=0.95)
    assert mm3._pick_victim() is None
