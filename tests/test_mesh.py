"""Mesh construction + logical sharding rules on the 8-device CPU platform."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.parallel.mesh import (
    MeshSpec,
    build_mesh,
    mesh_shape_summary,
    validate_divisibility,
)
from ray_tpu.parallel.sharding import named_sharding, shard_params


def test_meshspec_resolution():
    assert MeshSpec({"dp": -1}).resolved(8) == {"dp": 8}
    assert MeshSpec({"dp": 2, "tp": -1}).resolved(8) == {"dp": 2, "tp": 4}
    with pytest.raises(ValueError):
        MeshSpec({"dp": 3}).resolved(8)
    with pytest.raises(ValueError):
        MeshSpec({"dp": -1, "tp": -1}).resolved(8)


def test_axis_order_is_canonical():
    spec = MeshSpec({"tp": 2, "dp": 2, "sp": 2})
    assert spec.axis_names() == ("dp", "sp", "tp")


def test_build_mesh_shapes():
    mesh = build_mesh(MeshSpec({"dp": 2, "fsdp": 2, "tp": 2}))
    assert mesh_shape_summary(mesh) == {"dp": 2, "fsdp": 2, "tp": 2}
    assert mesh.devices.size == 8


def test_dcn_axes_are_slowest_varying():
    mesh = build_mesh(MeshSpec({"dp": 2, "tp": 4}, dcn_axes=("dp",)))
    assert mesh.axis_names[0] == "dp"


def test_named_sharding_rules():
    mesh = build_mesh(MeshSpec({"dp": 2, "fsdp": 2, "tp": 2}))
    sh = named_sharding(mesh, "batch", "embed")
    # batch -> (dp, fsdp); embed -> fsdp is taken, so None.
    spec = sh.spec
    assert spec[0] == ("dp", "fsdp")
    assert spec[1] is None
    sh2 = named_sharding(mesh, "embed", "mlp")
    assert sh2.spec[0] == "fsdp" and sh2.spec[1] == "tp"


def test_sharded_matmul_matches_single_device():
    mesh = build_mesh(MeshSpec({"dp": 2, "tp": 4}))
    x = jnp.arange(8 * 16, dtype=jnp.float32).reshape(8, 16)
    w = jnp.ones((16, 32), jnp.float32)
    xs = jax.device_put(x, named_sharding(mesh, "batch", None))
    ws = jax.device_put(w, named_sharding(mesh, "embed", "mlp"))

    @jax.jit
    def f(x, w):
        return x @ w

    out = f(xs, ws)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w))


def test_validate_divisibility():
    mesh = build_mesh(MeshSpec({"dp": 4, "sp": 2}))
    validate_divisibility(mesh, batch_size=8, seq_len=128)
    with pytest.raises(ValueError):
        validate_divisibility(mesh, batch_size=6)
    with pytest.raises(ValueError):
        validate_divisibility(mesh, batch_size=8, seq_len=127)


def test_shard_params_places_leaves():
    mesh = build_mesh(MeshSpec({"dp": 2, "tp": 4}))
    params = {"w": jnp.ones((16, 8)), "b": jnp.ones((8,))}
    axes = {"w": ("embed", "mlp"), "b": None}
    placed = shard_params(params, mesh, axes)
    assert placed["w"].sharding.spec[1] == "tp"
    assert placed["b"].sharding.is_fully_replicated
