"""Model zoo tests: import health, forward shapes, and training progress."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest


def test_models_package_imports():
    import ray_tpu.models as m

    assert hasattr(m, "GPT2") and hasattr(m, "GPT2Config") and hasattr(m, "MLP")


def test_mlp_forward_and_loss_decreases():
    from ray_tpu.models.mlp import MLP, make_train_step

    model = MLP(features=(32, 16, 4))
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (8, 12))
    params = model.init(rng, x)
    out = model.apply(params, x)
    assert out.shape == (8, 4)

    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    step = make_train_step(model, opt)
    y = jnp.arange(8) % 4
    losses = []
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state, {"x": x, "y": y})
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_gpt2_tiny_forward_shape():
    from ray_tpu.models.gpt2 import GPT2, GPT2Config

    cfg = GPT2Config.tiny(seq=32)
    model = GPT2(cfg)
    ids = jnp.zeros((2, 32), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    logits = model.apply(params, ids)
    assert logits.shape == (2, 32, cfg.vocab_size)


def test_gpt2_train_step_loss_decreases():
    from ray_tpu.models.gpt2 import (
        GPT2,
        GPT2Config,
        make_train_step,
        next_token_loss,
    )

    cfg = GPT2Config.tiny(seq=32)
    model = GPT2(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (4, 32), 0, cfg.vocab_size)
    params = model.init(rng, ids)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    step = make_train_step(model, opt, donate=False)
    batch = {"input_ids": ids, "labels": ids}
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # Sanity: loss starts near ln(vocab) for random params.
    assert losses[0] < np.log(cfg.vocab_size) * 2


def test_gpt2_param_specs_have_logical_axes():
    from ray_tpu.models.gpt2 import GPT2, GPT2Config, logical_param_specs

    cfg = GPT2Config.tiny(seq=16)
    specs = logical_param_specs(GPT2(cfg), (1, 16))
    leaves = jax.tree.leaves(specs, is_leaf=lambda x: hasattr(x, "index") or x is None)
    # The embedding table must carry ("vocab", "embed").
    flat = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: not isinstance(x, dict))[0]
    wte = [v for path, v in flat if any("wte" in str(p) for p in path)]
    assert wte and tuple(wte[0]) == ("vocab", "embed")


def test_next_token_loss_masking():
    from ray_tpu.models.gpt2 import next_token_loss

    logits = jnp.zeros((1, 4, 8))
    labels = jnp.array([[1, 2, -100, 3]])
    loss = next_token_loss(logits, labels)
    # Uniform logits -> loss = ln(8) over the unmasked positions.
    assert np.isclose(float(loss), np.log(8), atol=1e-5)


# --------------------------------------------------------------------------- #
# MoE (sparse mixture-of-experts, expert-parallel)
# --------------------------------------------------------------------------- #


def test_moe_forward_shape_and_train_step():
    from ray_tpu.models.moe import MoE, MoEConfig, make_moe_train_step

    cfg = MoEConfig.tiny(seq=32)
    model = MoE(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (4, 32), 0, cfg.vocab_size, dtype=jnp.int32)
    params = model.init(rng, ids)
    logits = model.apply(params, ids)
    assert logits.shape == (4, 32, cfg.vocab_size)

    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    step = make_moe_train_step(model, opt, donate=False)
    batch = {"input_ids": ids, "labels": ids}
    losses = []
    p, s = params, opt_state
    for _ in range(10):
        p, s, loss = step(p, s, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_moe_routing_respects_capacity_and_balances():
    """Every token's combine weights sum to ~1 when capacity is ample (no
    drops possible), each (expert, slot) holds at most one token, and
    per-expert occupancy never exceeds capacity."""
    import dataclasses

    from ray_tpu.models.moe import MoEConfig, MoEMLP, expert_capacity

    # capacity_factor = E/k makes cap == T: nothing can ever be dropped.
    cfg = dataclasses.replace(MoEConfig.tiny(seq=16), capacity_factor=2.0)
    mlp = MoEMLP(cfg)
    rng = jax.random.PRNGKey(1)
    x = jax.random.normal(rng, (2, 16, cfg.n_embd), jnp.float32)
    params = mlp.init(rng, x)
    y, cols = mlp.apply(params, x, mutable=["losses", "intermediates"])
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(y, np.float32)).all()

    dispatch = np.asarray(cols["intermediates"]["dispatch"][0])  # [T, E, C]
    combine = np.asarray(cols["intermediates"]["combine"][0])
    t, e, cap = dispatch.shape
    assert cap == expert_capacity(cfg, t)
    # cap == T here, so no token can be dropped: every token's combine
    # weights must sum to ~1 and it must occupy exactly top_k slots.
    np.testing.assert_allclose(combine.sum(axis=(1, 2)), 1.0, atol=1e-5)
    np.testing.assert_allclose(dispatch.sum(axis=(1, 2)), cfg.top_k,
                               atol=1e-5)
    # Each (expert, slot) holds at most one token; occupancy <= capacity.
    assert dispatch.sum(axis=0).max() <= 1.0 + 1e-5
    assert (dispatch.sum(axis=(0, 2)) <= cap + 1e-5).all()

    # With a tight capacity, drops happen but invariants still hold.
    tight = dataclasses.replace(MoEConfig.tiny(seq=16), capacity_factor=0.5)
    y2, cols2 = MoEMLP(tight).apply(
        params, x, mutable=["losses", "intermediates"])
    d2 = np.asarray(cols2["intermediates"]["dispatch"][0])
    assert d2.sum(axis=0).max() <= 1.0 + 1e-5
    assert d2.sum() < dispatch.sum()  # something was dropped
    assert np.isfinite(np.asarray(y2, np.float32)).all()


def test_moe_expert_parallel_matches_single_device():
    """One train step on a dp*ep mesh produces the same loss as the
    unsharded step — the all-to-all dispatch is numerically transparent."""
    import optax as _optax

    from ray_tpu.models.moe import MoE, MoEConfig, make_moe_train_step
    from ray_tpu.models.gpt2 import mesh_shardings_for
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.parallel.sharding import batch_sharding

    cfg = MoEConfig.tiny(seq=32)
    model = MoE(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (4, 32), 0, cfg.vocab_size, dtype=jnp.int32)
    params = jax.jit(lambda: model.init(rng, ids))()
    opt = _optax.sgd(0.1)
    opt_state = jax.jit(opt.init)(params)
    batch = {"input_ids": ids, "labels": ids}

    step0 = make_moe_train_step(model, opt, donate=False)
    _, _, loss_single = step0(params, opt_state, batch)

    mesh = build_mesh(MeshSpec({"dp": 2, "ep": 2, "tp": 2}))
    shardings = mesh_shardings_for(model, mesh, (4, 32))
    p_sh = jax.device_put(params, shardings)
    o_sh = jax.device_put(opt_state)  # sgd state is empty/scalars
    b_sh = {k: jax.device_put(v, batch_sharding(mesh))
            for k, v in batch.items()}
    step_m = make_moe_train_step(model, opt, mesh=mesh, donate=False)
    _, _, loss_mesh = step_m(p_sh, o_sh, b_sh)
    np.testing.assert_allclose(float(loss_single), float(loss_mesh),
                               rtol=2e-2)
