"""Model zoo tests: import health, forward shapes, and training progress."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest


def test_models_package_imports():
    import ray_tpu.models as m

    assert hasattr(m, "GPT2") and hasattr(m, "GPT2Config") and hasattr(m, "MLP")


def test_mlp_forward_and_loss_decreases():
    from ray_tpu.models.mlp import MLP, make_train_step

    model = MLP(features=(32, 16, 4))
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (8, 12))
    params = model.init(rng, x)
    out = model.apply(params, x)
    assert out.shape == (8, 4)

    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    step = make_train_step(model, opt)
    y = jnp.arange(8) % 4
    losses = []
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state, {"x": x, "y": y})
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_gpt2_tiny_forward_shape():
    from ray_tpu.models.gpt2 import GPT2, GPT2Config

    cfg = GPT2Config.tiny(seq=32)
    model = GPT2(cfg)
    ids = jnp.zeros((2, 32), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    logits = model.apply(params, ids)
    assert logits.shape == (2, 32, cfg.vocab_size)


def test_gpt2_train_step_loss_decreases():
    from ray_tpu.models.gpt2 import (
        GPT2,
        GPT2Config,
        make_train_step,
        next_token_loss,
    )

    cfg = GPT2Config.tiny(seq=32)
    model = GPT2(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (4, 32), 0, cfg.vocab_size)
    params = model.init(rng, ids)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    step = make_train_step(model, opt, donate=False)
    batch = {"input_ids": ids, "labels": ids}
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # Sanity: loss starts near ln(vocab) for random params.
    assert losses[0] < np.log(cfg.vocab_size) * 2


def test_gpt2_param_specs_have_logical_axes():
    from ray_tpu.models.gpt2 import GPT2, GPT2Config, logical_param_specs

    cfg = GPT2Config.tiny(seq=16)
    specs = logical_param_specs(GPT2(cfg), (1, 16))
    leaves = jax.tree.leaves(specs, is_leaf=lambda x: hasattr(x, "index") or x is None)
    # The embedding table must carry ("vocab", "embed").
    flat = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: not isinstance(x, dict))[0]
    wte = [v for path, v in flat if any("wte" in str(p) for p in path)]
    assert wte and tuple(wte[0]) == ("vocab", "embed")


def test_next_token_loss_masking():
    from ray_tpu.models.gpt2 import next_token_loss

    logits = jnp.zeros((1, 4, 8))
    labels = jnp.array([[1, 2, -100, 3]])
    loss = next_token_loss(logits, labels)
    # Uniform logits -> loss = ln(8) over the unmasked positions.
    assert np.isclose(float(loss), np.log(8), atol=1e-5)
