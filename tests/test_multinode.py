"""Multi-node behaviors: cross-node transfer, lineage reconstruction,
scheduling fairness.

Mirrors the reference's `python/ray/tests/test_reconstruction.py` and
object-manager transfer tests, on the in-process Cluster sim.
"""

import os
import tempfile

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.exceptions import ObjectLostError


@pytest.fixture()
def two_node_cluster():
    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    cluster.add_node(num_cpus=2, resources={"side": 2})
    cluster.wait_for_nodes()
    cluster.connect()
    yield cluster
    cluster.shutdown()


@ray_tpu.remote
def make_blob(mb: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 255, size=mb * 1024 * 1024, dtype=np.uint8)


@ray_tpu.remote
def bump_and_blob(counter_path: str, mb: int):
    # Side-effect counter proves re-execution (not a cached copy).
    with open(counter_path, "a") as f:
        f.write("x")
    rng = np.random.default_rng(7)
    return rng.integers(0, 255, size=mb * 1024 * 1024, dtype=np.uint8)


@ray_tpu.remote
def add_one(arr):
    return arr.astype(np.int64) + 1


@ray_tpu.remote
def checksum(arr):
    return int(arr.astype(np.int64).sum())


def test_cross_node_chunked_pull(two_node_cluster):
    """A multi-chunk object produced on node B is readable from the driver
    (pulled to the head store in bounded chunks, not one giant RPC)."""
    ref = make_blob.options(resources={"side": 1}).remote(40)
    arr = ray_tpu.get(ref, timeout=120)
    expect = np.random.default_rng(0).integers(
        0, 255, size=40 * 1024 * 1024, dtype=np.uint8)
    assert arr.nbytes == 40 * 1024 * 1024
    np.testing.assert_array_equal(arr[:4096], expect[:4096])
    np.testing.assert_array_equal(arr[-4096:], expect[-4096:])


def test_lineage_reconstruction_after_node_death(two_node_cluster):
    """Reference object_recovery_manager behavior: when the only copy of a
    task return dies with its node, the owner re-executes the creating
    task and get() succeeds."""
    cluster = two_node_cluster
    counter = os.path.join(tempfile.mkdtemp(), "execs")
    ref = bump_and_blob.options(resources={"side": 1}).remote(counter, 2)
    # Materialize via a consumer ON node B: the driver must never fetch
    # the value (a driver-side cached copy would satisfy the later get
    # without recovery).
    ray_tpu.get(checksum.options(resources={"side": 1}).remote(ref),
                timeout=60)
    assert open(counter).read() == "x"

    side_node = cluster.raylets[1]
    cluster.remove_node(side_node)          # the only copy dies with it
    cluster.add_node(num_cpus=2, resources={"side": 2})  # re-exec target
    cluster.wait_for_nodes()

    again = ray_tpu.get(ref, timeout=120)
    assert open(counter).read() == "xx", "task was not re-executed"
    assert again.nbytes == 2 * 1024 * 1024
    np.testing.assert_array_equal(
        again[:1024],
        np.random.default_rng(7).integers(
            0, 255, size=2 * 1024 * 1024, dtype=np.uint8)[:1024])


def test_recursive_reconstruction_of_missing_dep(two_node_cluster):
    """If the lost object's dependency is ALSO lost, the owner rebuilds the
    lineage bottom-up (dep first, then the consumer)."""
    cluster = two_node_cluster
    base = make_blob.options(resources={"side": 1}).remote(1, seed=3)
    out = add_one.options(resources={"side": 1}).remote(base)
    # Materialize both on node B without pulling either to the driver.
    ray_tpu.get(checksum.options(resources={"side": 1}).remote(out),
                timeout=60)

    side_node = cluster.raylets[1]
    cluster.remove_node(side_node)          # loses BOTH objects
    cluster.add_node(num_cpus=2, resources={"side": 2})
    cluster.wait_for_nodes()

    val = ray_tpu.get(out, timeout=120)
    expect = np.random.default_rng(3).integers(
        0, 255, size=1024 * 1024, dtype=np.uint8).astype(np.int64) + 1
    np.testing.assert_array_equal(val[:1024], expect[:1024])


def test_put_objects_are_not_reconstructable(two_node_cluster):
    """ray.put has no lineage: losing every copy surfaces ObjectLostError
    (reference semantics — only task returns are recoverable)."""
    cluster = two_node_cluster

    @ray_tpu.remote
    def put_on_node():
        import numpy as _np

        import ray_tpu as _rt

        inner = _rt.put(_np.ones(1024 * 1024, dtype=_np.uint8))
        return [inner]  # keep the inner ref alive via the outer list

    (inner_ref,) = ray_tpu.get(
        put_on_node.options(resources={"side": 1}).remote(), timeout=60)
    side_node = cluster.raylets[1]
    cluster.remove_node(side_node)
    cluster.add_node(num_cpus=2, resources={"side": 2})
    cluster.wait_for_nodes()
    with pytest.raises((ObjectLostError, ray_tpu.exceptions.GetTimeoutError)):
        ray_tpu.get(inner_ref, timeout=15)


def test_oversized_pull_raises_instead_of_hanging():
    """An object larger than the destination store surfaces a typed error
    (non-retryable) rather than retrying the pull forever."""
    from ray_tpu.exceptions import RaySystemError

    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2,
                                      "object_store_memory": 4 * 1024 * 1024})
    try:
        cluster.add_node(num_cpus=2, resources={"side": 2},
                         object_store_memory=64 * 1024 * 1024)
        cluster.wait_for_nodes()
        cluster.connect()
        ref = make_blob.options(resources={"side": 1}).remote(16)
        with pytest.raises((RaySystemError, ray_tpu.exceptions.GetTimeoutError)):
            ray_tpu.get(ref, timeout=30)
    finally:
        cluster.shutdown()


def test_small_tasks_schedule_past_infeasible_head(ray_start_regular):
    """No FIFO head-of-line blocking: a queued task whose resources can
    never be satisfied must not stall feasible work behind it (reference
    scored top-k selection, hybrid_scheduling_policy.h)."""

    @ray_tpu.remote
    def quick(i):
        return i * 2

    blocked = quick.options(num_cpus=99).remote(0)  # infeasible forever
    results = ray_tpu.get([quick.remote(i) for i in range(20)], timeout=60)
    assert results == [2 * i for i in range(20)]
    ready, not_ready = ray_tpu.wait([blocked], timeout=0.1)
    assert not ready and not_ready == [blocked]


def test_data_locality_places_task_near_large_arg(two_node_cluster):
    """A task consuming a large resident object runs on the node holding
    the bytes instead of pulling them (reference `lease_policy.h:56`
    locality-aware lease policy)."""

    @ray_tpu.remote
    def where(arr):
        import os

        return (os.environ.get("RAY_TPU_NODE_ID"), int(arr[0]))

    # Produce 16 MiB on the side node.
    blob = make_blob.options(resources={"side": 1}).remote(16)
    ray_tpu.wait([blob], num_returns=1, timeout=60)
    side_node = None
    for n in ray_tpu.nodes():
        if n["Resources"].get("side"):
            side_node = n["NodeID"]
    assert side_node is not None
    # No constraints on the consumer: locality scoring should place it on
    # the side node (repeat to avoid a fluke from transient utilization).
    hits = 0
    for _ in range(3):
        node_id, _ = ray_tpu.get(where.remote(blob), timeout=60)
        hits += int(node_id == side_node)
    assert hits >= 2, f"consumer ran off-data {3 - hits}/3 times"
