"""Model-multiplexed replicas (ISSUE 11): LoRA banks on one engine.

The acceptance contract: N adapters share ONE paged arena and ONE
compiled program set (compile counters prove zero new XLA programs vs
the single-model engine), per-adapter output is token-identical to a
dedicated single-model replica with the same weights — including
through a tp=2 mesh — and residency is LRU per replica with pinned
rows protected from eviction.
"""

import json
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

import ray_tpu
from conftest import assert_compiles_once
from ray_tpu import serve
from ray_tpu.inference import AdapterLoadError, EngineConfig, InferenceEngine
from ray_tpu.models.llama import Llama, LlamaConfig, make_adapter_weights

SEEDS = {"m-a": 11, "m-b": 22, "m-c": 33}


@pytest.fixture(scope="module")
def tiny_model():
    mcfg = LlamaConfig.tiny(seq=256)
    model = Llama(mcfg)
    params = jax.jit(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)))()
    return model, params


def _source(model):
    def load(model_id):
        if model_id not in SEEDS:
            raise AdapterLoadError(f"unknown model {model_id!r}")
        return make_adapter_weights(model.config, rank=8,
                                    seed=SEEDS[model_id])
    return load


def _mux_engine(model, params, capacity=2, mesh=None):
    eng = InferenceEngine(EngineConfig(max_adapters=capacity, lora_rank=8),
                          model=model, params=params, mesh=mesh)
    eng.register_adapter_source(_source(model))
    return eng


# ----------------------------------------------------- engine-level proofs


def test_multiplexed_parity_and_zero_new_programs(tiny_model):
    """Interleaved requests across adapters + base model: exactly the
    PR-3 program count (prefill 1, decode 1), zero leaks, and every
    adapter's output token-identical to a dedicated engine."""
    model, params = tiny_model
    eng = _mux_engine(model, params)
    reqs = {
        "m-a": eng.add_request([1, 2, 3, 4, 5], 10, model_id="m-a"),
        "m-b": eng.add_request([1, 2, 3, 4, 5], 10, model_id="m-b"),
        None: eng.add_request([7, 8, 9], 8),
    }
    eng.run_until_idle()
    assert_compiles_once(eng.stats(), "prefill_compiles",
                         "decode_compiles")
    eng.check_no_leaks()
    outs = {mid: list(r.generated) for mid, r in reqs.items()}
    # Adapters actually steer generation (not identity deltas).
    assert outs["m-a"] != outs["m-b"]

    # Dedicated single-model engines with the same weights.
    for mid in ("m-a", "m-b"):
        ded = _mux_engine(model, params, capacity=1)
        r = ded.add_request([1, 2, 3, 4, 5], 10, model_id=mid)
        ded.run_until_idle()
        assert list(r.generated) == outs[mid], mid
    plain = InferenceEngine(EngineConfig(), model=model, params=params)
    r = plain.add_request([7, 8, 9], 8)
    plain.run_until_idle()
    assert list(r.generated) == outs[None]


def test_lru_eviction_and_deterministic_reload(tiny_model):
    model, params = tiny_model
    eng = _mux_engine(model, params, capacity=2)
    first = eng.add_request([1, 2, 3, 4, 5], 10, model_id="m-a")
    eng.add_request([9, 9], 4, model_id="m-b")
    eng.run_until_idle()
    baseline = list(first.generated)
    # Third adapter: capacity 2 forces LRU eviction of m-a.
    eng.add_request([1, 2], 4, model_id="m-c")
    eng.run_until_idle()
    st = eng.stats()["adapters"]
    assert st["resident"] == ["m-b", "m-c"]
    assert st["evictions"] == 1
    # Reload on demand: same seed => same weights => same tokens, and
    # STILL no new XLA programs (bank churn is data, not shape).
    again = eng.add_request([1, 2, 3, 4, 5], 10, model_id="m-a")
    eng.run_until_idle()
    assert list(again.generated) == baseline
    assert_compiles_once(eng.stats(), "prefill_compiles",
                         "decode_compiles")
    eng.check_no_leaks()


def test_pinned_rows_never_evicted(tiny_model):
    """Rows with live (queued/running) sequences are pinned: filling the
    bank past capacity rejects the NEW request instead of yanking
    weights from under a mid-flight generation."""
    model, params = tiny_model
    eng = _mux_engine(model, params, capacity=2)
    eng.add_request([1] * 40, 24, model_id="m-a")
    eng.add_request([2] * 40, 24, model_id="m-b")
    with pytest.raises((AdapterLoadError, ValueError), match="pinned"):
        eng.add_request([3, 3], 4, model_id="m-c")
    eng.run_until_idle()
    eng.check_no_leaks()
    # Drained: now m-c loads fine (LRU can evict).
    eng.add_request([3, 3], 4, model_id="m-c")
    eng.run_until_idle()
    assert "m-c" in eng.stats()["adapters"]["resident"]


def test_unknown_model_rejected_at_submit(tiny_model):
    model, params = tiny_model
    eng = _mux_engine(model, params)
    with pytest.raises(ValueError, match="unknown model"):
        eng.add_request([1, 2], 4, model_id="nope")
    plain = InferenceEngine(EngineConfig(), model=model, params=params)
    with pytest.raises(ValueError, match="not multiplexed"):
        plain.add_request([1, 2], 4, model_id="m-a")


def test_cross_adapter_prefix_hits_with_parity(tiny_model):
    """Radix cache (PR 16): the KV arena is adapter-invariant (LoRA
    deltas are late-fused side contributions merged once before
    final_norm — the residual stream and every K/V write are base-model
    pure), so a prefix cached under one adapter hits for every other
    adapter AND the base model — with output parity vs a cold engine
    that never saw the donor."""
    model, params = tiny_model
    eng = _mux_engine(model, params, capacity=3)
    prompt = list(range(1, 18))        # 17 tokens -> 16 ride the cache
    outs = {}
    for mid in ("m-a", "m-b", None):
        r = eng.add_request(prompt, 8, model_id=mid)
        eng.run_until_idle()
        outs[mid] = list(r.generated)
    st = eng.stats()
    assert st["prefix_cache"]["hits"] >= 2, st["prefix_cache"]
    assert_compiles_once(st, "prefill_compiles", "decode_compiles")
    eng.check_no_leaks()
    assert outs["m-a"] != outs["m-b"]  # adapters still steer generation
    # Cold engines (no warm cache) reproduce every warm-path output.
    for mid in ("m-a", "m-b"):
        cold = _mux_engine(model, params, capacity=1)
        r = cold.add_request(prompt, 8, model_id=mid)
        cold.run_until_idle()
        assert list(r.generated) == outs[mid], mid


def test_tp2_multiplexed_parity(multi_device_workers, tiny_model):
    """Acceptance: adapter outputs are token-identical through a tp=2
    mesh (the A_o bank shards its input dim WITH the heads), with the
    compile-once discipline intact."""
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh

    model, params = tiny_model
    mesh = build_mesh(MeshSpec({"tp": 2}), devices=jax.devices()[:2])
    outs = {}
    for name, m in (("single", None), ("tp2", mesh)):
        eng = _mux_engine(model, params, mesh=m)
        rs = [eng.add_request([1, 2, 3, 4, 5], 10, model_id="m-a"),
              eng.add_request([9, 8, 7], 8, model_id="m-b")]
        eng.run_until_idle()
        outs[name] = [list(r.generated) for r in rs]
        assert_compiles_once(eng.stats(), "prefill_compiles",
                             "decode_compiles", context=name)
        eng.check_no_leaks()
    assert outs["single"] == outs["tp2"]


@pytest.mark.slow  # ~11s: four extra jitted programs; gate.sh covers it
def test_tp2_prefix_cache_and_spec_decode_parity(multi_device_workers,
                                                 tiny_model):
    """Round-3 features compose with tp=2 sharded arenas: radix hits and
    speculative decoding stay token-identical through the mesh, with the
    compile-once discipline intact for every program."""
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh

    model, params = tiny_model
    mesh = build_mesh(MeshSpec({"tp": 2}), devices=jax.devices()[:2])
    prompt = list(range(1, 18))
    outs = {}
    for name, m in (("single", None), ("tp2", mesh)):
        eng = InferenceEngine(
            EngineConfig(spec_decode_draft_len=2),
            model=model, params=params, mesh=m,
            draft_model=model, draft_params=params)
        warm = eng.add_request(prompt, 8)
        eng.run_until_idle()               # primes the radix tree
        hit = eng.add_request(prompt, 8)
        other = eng.add_request([9, 8, 7], 6)
        eng.run_until_idle()
        outs[name] = [list(r.generated)
                      for r in (warm, hit, other)]
        st = eng.stats()
        assert hit.cached_tokens == 16, (name, hit.cached_tokens)
        assert st["prefix_cache"]["hits"] >= 1, (name, st["prefix_cache"])
        assert st["spec_decode"]["accept_rate"] == 1.0, (name, st)
        assert_compiles_once(st["spec_decode"], "propose_compiles",
                             "verify_compiles", context=name)
        assert_compiles_once(st, "prefill_compiles", context=name)
        eng.check_no_leaks()
    assert outs["single"] == outs["tp2"]


# --------------------------------------------------------- serve-path e2e


@pytest.fixture()
def serve_cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _post(port, path, payload, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_multiplexed_llmserver_http_and_affinity(serve_cluster):
    """One LLMServer replica set serves several model_ids over HTTP;
    the routing table advertises adapter residency and the router's
    pick prefers the replica already holding the adapter."""
    from ray_tpu.inference import LLMServer

    adapters = {m: {"seed": s, "rank": 8} for m, s in SEEDS.items()}
    serve.run(LLMServer.options(
        name="zoo_llm", num_replicas=1,
        max_concurrent_queries=16).bind(
            "tiny", 256, 8, None, adapters))
    port = serve.http_port()
    out_a = _post(port, "/zoo_llm?model_id=m-a",
                  {"ids": [1, 2, 3], "max_new_tokens": 6,
                   "model_id": "m-a"})["result"]
    out_b = _post(port, "/zoo_llm?model_id=m-b",
                  {"ids": [1, 2, 3], "max_new_tokens": 6,
                   "model_id": "m-b"})["result"]
    assert out_a["ids"][:3] == [1, 2, 3] and len(out_a["ids"]) == 9
    assert out_a["ids"] != out_b["ids"]
    # Determinism through the serving stack.
    assert out_a == _post(port, "/zoo_llm?model_id=m-a",
                          {"ids": [1, 2, 3], "max_new_tokens": 6,
                           "model_id": "m-a"})["result"]

    # Residency reaches the routing table (health-check push)...
    from ray_tpu.serve.handle import _process_router

    router = _process_router()
    router._ensure_started()
    deadline = time.time() + 20
    entry = None
    while time.time() < deadline:
        entry = router.entry_snapshot("zoo_llm")
        resident = next(iter((entry or {}).get("adapters", {}).values()),
                        [])
        # BOTH adapters, not just the first push: m-b's residency rides
        # a later health tick than m-a's, and breaking on the first
        # adapters entry raced it (flaky pre-PR-12).
        if "m-a" in resident and "m-b" in resident:
            break
        time.sleep(0.25)
    assert entry and entry.get("mux"), entry
    resident = next(iter(entry["adapters"].values()))
    assert "m-a" in resident and "m-b" in resident
    # ...and the affinity pick steers model traffic to the holder.
    rid = next(iter(entry["adapters"]))
    choice = router._pick(entry, model_id="m-a")
    assert choice is not None and choice[0] == rid
