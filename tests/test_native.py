"""Native data-plane helper: correctness with and without the C library."""

import numpy as np


def test_gather_copy_matches_python():
    from ray_tpu import _native

    rng = np.random.default_rng(0)
    parts = [rng.integers(0, 255, 1000, dtype=np.uint8).data,
             b"hello-bytes",
             memoryview(rng.random(100))]
    total = sum(p.nbytes if isinstance(p, memoryview) else len(p)
                for p in parts)
    dst = bytearray(total)
    n = _native.gather_copy(memoryview(dst), parts)
    assert n == total
    expect = b"".join(bytes(p) for p in parts)
    assert bytes(dst) == expect


def test_copy_at_offsets():
    from ray_tpu import _native

    dst = bytearray(32)
    _native.copy_at(memoryview(dst), 4, b"abcd")
    _native.copy_at(memoryview(dst), 0, b"xy")
    assert bytes(dst[:8]) == b"xy\x00\x00abcd"


def test_fallback_path_without_lib(monkeypatch):
    from ray_tpu import _native

    monkeypatch.setattr(_native, "get_lib", lambda: None)
    dst = bytearray(20)
    n = _native.gather_copy(memoryview(dst), [b"12345", b"67890"])
    assert n == 10 and bytes(dst[:10]) == b"1234567890"
    _native.copy_at(memoryview(dst), 10, b"xx")
    assert bytes(dst[10:12]) == b"xx"


def test_store_roundtrip_via_native(ray_start_shared):
    import ray_tpu

    arr = np.random.default_rng(1).random(2 * 1024 * 1024 // 8)
    ref = ray_tpu.put(arr)
    back = ray_tpu.get(ref)
    np.testing.assert_array_equal(np.asarray(back), arr)
