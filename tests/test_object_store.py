"""Shared-memory store: create/seal/get, adopt, client attach, spilling."""

import time

import numpy as np
import pytest

from ray_tpu.core.ids import JobID, ObjectID, TaskID
from ray_tpu.core.object_store import (
    ObjectStoreClient,
    ObjectStoreFullError,
    SharedMemoryStore,
)


def make_oid(i=1):
    return ObjectID.for_return(TaskID.for_task(JobID.from_int(1)), i)


@pytest.fixture()
def store():
    s = SharedMemoryStore(f"test_{np.random.randint(1 << 30)}",
                          capacity_bytes=50 << 20, spill_dir="/tmp/rtpu_test_spill")
    yield s
    s.shutdown()


def test_put_get_value(store):
    oid = make_oid()
    x = np.arange(100000, dtype=np.float32)
    store.put_value(oid, {"x": x, "tag": "hello"})
    assert store.contains(oid)
    client = ObjectStoreClient(store._session)
    v = client.get_value(oid)
    assert v["tag"] == "hello"
    np.testing.assert_array_equal(v["x"], x)
    client.close()


def test_missing_object(store):
    client = ObjectStoreClient(store._session)
    assert client.get_buffer(make_oid(42)) is None
    client.close()


def test_delete(store):
    oid = make_oid()
    store.put_value(oid, b"x" * 1000)
    store.delete(oid)
    assert not store.contains(oid)


def test_capacity_and_spill(store):
    # Fill beyond capacity: oldest unpinned objects spill to disk and
    # restore transparently on access.
    oids = [make_oid(i + 1) for i in range(8)]
    data = np.zeros(1 << 20, dtype=np.uint8)  # 1 MiB each
    small = SharedMemoryStore(store._session + "s", capacity_bytes=4 << 20,
                              spill_dir="/tmp/rtpu_test_spill")
    try:
        for oid in oids:
            small.put_value(oid, data)
        stats = small.stats()
        assert stats["num_spilled"] >= 4
        # restored read
        buf = small.get_buffer(oids[0])
        assert buf is not None
    finally:
        small.shutdown()


def test_oversize_object_rejected(store):
    tiny = SharedMemoryStore(store._session + "t", capacity_bytes=1 << 20)
    try:
        with pytest.raises(ObjectStoreFullError):
            tiny.put_value(make_oid(), np.zeros(1 << 21, dtype=np.uint8))
    finally:
        tiny.shutdown()


def test_pinned_objects_not_spilled(store):
    small = SharedMemoryStore(store._session + "p", capacity_bytes=3 << 20,
                              spill_dir="/tmp/rtpu_test_spill")
    try:
        a = make_oid(1)
        small.put_value(a, np.zeros(1 << 20, dtype=np.uint8))
        small.pin(a)
        for i in range(2, 5):
            small.put_value(make_oid(i), np.zeros(1 << 20, dtype=np.uint8))
        # pinned object is still in shm
        entry = small._objects[a]
        assert entry.shm is not None
    finally:
        small.shutdown()


def test_spill_to_cloud_storage_roundtrip(tmp_path):
    """Spill targets a bucket URI through the storage backends (reference
    external_storage.py:445): evicted bytes leave the machine and restore
    transparently on access."""
    import numpy as np

    from ray_tpu.core.ids import ObjectID
    from ray_tpu.core.object_store import SharedMemoryStore
    from ray_tpu.train.storage import MemoryBackend

    MemoryBackend.clear()
    store = SharedMemoryStore("cloudspill", capacity_bytes=3 * 1024 * 1024,
                              spill_dir="memory://spillbkt/objs")
    try:
        oids, blobs = [], []
        for i in range(3):
            oid = ObjectID.from_random()
            blob = np.full(1024 * 1024, i, dtype=np.uint8).tobytes()
            buf = store.create(oid, len(blob))
            buf[:] = blob
            store.seal(oid)
            oids.append(oid)
            blobs.append(blob)
        # A 4th object forces LRU spill of the first into the bucket.
        extra = ObjectID.from_random()
        buf = store.create(extra, 1024 * 1024)
        buf[:] = b"\xaa" * (1024 * 1024)
        store.seal(extra)
        deadline = time.monotonic() + 10  # upload runs off-lock, async
        while time.monotonic() < deadline and \
                not MemoryBackend("spillbkt").list("objs"):
            time.sleep(0.05)
        assert MemoryBackend("spillbkt").list("objs"), \
            "nothing spilled to the bucket"
        # Access restores from the bucket and removes the spilled copy.
        back = store.get_bytes(oids[0])
        assert back == blobs[0]
        # Restore the second spilled object too (forces fresh eviction
        # choices before the deletion sweep below).
        assert store.get_bytes(oids[1]) == blobs[1]
        names_before = MemoryBackend("spillbkt").list("objs")
        for oid in oids + [extra]:
            store.delete(oid)
        assert not MemoryBackend("spillbkt").list("objs"), names_before
    finally:
        store.shutdown()
        MemoryBackend.clear()
