"""Transfer plane: windowed multi-source pulls (reference object_manager
chunk streams, `object_buffer_pool.h`).

Drives the raylet pull path directly on an in-process multi-node Cluster
(no workers): objects are seeded into one node's store, other raylets pull
through `_pull_object_pipelined`, and a per-chunk-RPC delay hook on the
serving side stands in for network RTT.
"""

import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.rpc import DEFERRED

CHUNK = 128 * 1024


@pytest.fixture()
def transfer_cluster():
    """4 raylets, tiny chunks, fast connect timeouts; no driver session."""
    ray_tpu.shutdown()
    saved = dict(GLOBAL_CONFIG._overrides)
    GLOBAL_CONFIG._overrides.update({
        "object_transfer_chunk_bytes": CHUNK,
        "rpc_connect_timeout_s": 1.0,
    })
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    for _ in range(3):
        cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    try:
        yield cluster
    finally:
        cluster.shutdown()
        GLOBAL_CONFIG._overrides.clear()
        GLOBAL_CONFIG._overrides.update(saved)


def _seed_object(raylet, n_chunks: int, seed: int = 0) -> ObjectID:
    """Seal a multi-chunk blob into `raylet`'s store and register it."""
    oid = ObjectID.from_random()
    payload = np.random.default_rng(seed).integers(
        0, 255, size=n_chunks * CHUNK, dtype=np.uint8).tobytes()
    raylet.store.put_serialized(oid, [payload])
    raylet.gcs.call("object_location_add",
                    {"object_id": oid, "node_id": raylet.node_id,
                     "size": raylet.store.local_size(oid)}, timeout=10)
    return oid


def _pull(raylet, oid: ObjectID) -> bool:
    entry = raylet.gcs.call("object_locations_get", {"object_id": oid},
                            timeout=10)
    return raylet._pull_object_pipelined(oid, entry)


def _count_ok_serves(raylet):
    """Wrap the raw chunk handler to count chunks actually served (the
    handler returns DEFERRED exactly when it sent an 'ok' chunk reply)."""
    orig = raylet.server._raw_handlers["pull_object_chunk"]
    counter = {"ok": 0}

    def wrapped(conn, payload):
        out = orig(conn, payload)
        if out is DEFERRED:
            counter["ok"] += 1
        return out

    raylet.server._raw_handlers["pull_object_chunk"] = wrapped
    return counter


def test_windowed_pull_beats_stop_and_wait_under_latency(transfer_cluster):
    """window>1 pipelines chunk RPCs: with an injected per-RPC delay the
    windowed pull must land well under the serial stop-and-wait time, and
    the sealed bytes must be identical to the source."""
    seed_node, puller = transfer_cluster.raylets[0], transfer_cluster.raylets[1]
    n_chunks = 12
    delay = 0.05
    puller._chunk_fetch_delay_s = delay  # per-RPC RTT, hidden by the window
    try:
        oid_serial = _seed_object(seed_node, n_chunks, seed=1)
        oid_windowed = _seed_object(seed_node, n_chunks, seed=2)

        GLOBAL_CONFIG._overrides["object_transfer_window"] = 1
        t0 = time.perf_counter()
        assert _pull(puller, oid_serial)
        serial_s = time.perf_counter() - t0

        GLOBAL_CONFIG._overrides["object_transfer_window"] = 4
        t0 = time.perf_counter()
        assert _pull(puller, oid_windowed)
        windowed_s = time.perf_counter() - t0
    finally:
        puller._chunk_fetch_delay_s = 0.0

    assert serial_s >= n_chunks * delay * 0.9
    # Ideal windowed time is ceil(12/4)=3 RTTs vs 12 serial — assert a
    # loose 0.75 factor so scheduler jitter on a loaded 2-core CI box
    # doesn't flake a test whose ideal ratio is 4x.
    assert windowed_s < serial_s * 0.75, (
        f"window=4 ({windowed_s:.3f}s) should beat window=1 "
        f"({serial_s:.3f}s) with {delay}s per-RPC latency")
    for oid in (oid_serial, oid_windowed):
        assert puller.store.get_bytes(oid) == seed_node.store.get_bytes(oid)
    assert puller.store.stats()["num_unsealed"] == 0


def test_broadcast_drains_from_non_seed_nodes(transfer_cluster):
    """3 concurrent pullers against a seed whose fairness gate admits one
    transfer at a time: the shed pullers must drain chunks from earlier
    pullers (partial/completed locations), so at least one chunk is served
    by a NON-seed node and every replica still seals correctly."""
    seed_node = transfer_cluster.raylets[0]
    pullers = transfer_cluster.raylets[1:]
    GLOBAL_CONFIG._overrides["object_transfer_sender_concurrency"] = 1
    # Tight refresh cadence so pullers discover each other's partial
    # copies early in a 16-chunk transfer.
    GLOBAL_CONFIG._overrides["object_transfer_refetch_location_chunks"] = 2
    seed_node._chunk_serve_delay_s = 0.01
    counters = {r.node_id.hex(): _count_ok_serves(r)
                for r in transfer_cluster.raylets}
    try:
        oid = _seed_object(seed_node, n_chunks=16)
        results = {}

        def run(r):
            results[r.node_id.hex()] = _pull(r, oid)

        threads = [threading.Thread(target=run, args=(r,)) for r in pullers]
        for t in threads:
            t.start()
            # Staggered joins (like real broadcast consumers): earlier
            # pullers' partial registrations land before later pullers
            # resolve their location set.
            time.sleep(0.05)
        for t in threads:
            t.join(timeout=120)
    finally:
        seed_node._chunk_serve_delay_s = 0.0

    assert all(results.get(r.node_id.hex()) for r in pullers), results
    want = seed_node.store.get_bytes(oid)
    for r in pullers:
        assert r.store.get_bytes(oid) == want
        assert r.store.stats()["num_unsealed"] == 0
    non_seed_served = sum(
        counters[r.node_id.hex()]["ok"] for r in pullers)
    assert non_seed_served >= 1, (
        "every chunk was served by the seed — the broadcast never "
        f"became a tree ({ {h: c['ok'] for h, c in counters.items()} })")


def test_mid_pull_source_death_falls_back_to_remaining_location(
        transfer_cluster):
    """A source dying mid-pull: remaining locations finish the transfer,
    and the sealed content is still correct."""
    seed_node, second, puller = transfer_cluster.raylets[:3]
    oid = _seed_object(seed_node, n_chunks=24)
    assert _pull(second, oid)  # replicate: two full locations now

    second._chunk_serve_delay_s = 0.05
    seed_node._chunk_serve_delay_s = 0.05
    want = seed_node.store.get_bytes(oid)
    done = {}

    def run():
        done["ok"] = _pull(puller, oid)

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.15)  # several chunks in flight
    transfer_cluster.remove_node(second)
    seed_node._chunk_serve_delay_s = 0.0
    t.join(timeout=120)

    assert done.get("ok") is True
    assert puller.store.get_bytes(oid) == want
    assert puller.store.stats()["num_unsealed"] == 0


def test_pull_failure_leaves_no_unsealed_buffer(transfer_cluster):
    """Every location dying mid-pull aborts the transfer WITHOUT leaking
    the pre-created (unsealed) store buffer — the delete-on-failure
    invariant under the windowed/multi-source path."""
    seed_node, puller = transfer_cluster.raylets[1], transfer_cluster.raylets[2]
    oid = _seed_object(seed_node, n_chunks=24)
    seed_node._chunk_serve_delay_s = 0.05
    done = {}

    def run():
        done["ok"] = _pull(puller, oid)

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.15)
    transfer_cluster.remove_node(seed_node)  # the ONLY copy dies mid-pull
    t.join(timeout=120)

    assert done.get("ok") is False
    assert not puller.store.contains(oid)
    assert puller.store.stats()["num_unsealed"] == 0
    assert oid not in puller._active_pulls
