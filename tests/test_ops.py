"""Kernel numerics: flash attention (fwd+bwd) and ring attention vs XLA
reference, ring over 8 virtual CPU devices."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import flash_attention, mha_reference


def _qkv(rng, b=2, h=4, s=128, d=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (b, h, s, d), dtype)
    k = jax.random.normal(kk, (b, h, s, d), dtype)
    v = jax.random.normal(kv, (b, h, s, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_reference_forward(causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    out = flash_attention(q, k, v, causal)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_grads_match_reference():
    q, k, v = _qkv(jax.random.PRNGKey(1), s=64)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_cross_attention_seq_mismatch_uses_reference_convention():
    # seq_q != seq_k must agree with mha_reference (pallas path is gated off).
    rng = jax.random.PRNGKey(2)
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (1, 2, 32, 64))
    k = jax.random.normal(kk, (1, 2, 128, 64))
    v = jax.random.normal(kv, (1, 2, 128, 64))
    out = flash_attention(q, k, v, True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_pallas_kernels_interpret_mode(monkeypatch):
    """Run the actual Pallas fwd+bwd kernels (interpreter) vs XLA."""
    monkeypatch.setenv("RAY_TPU_PALLAS_INTERPRET", "1")
    q, k, v = _qkv(jax.random.PRNGKey(7), b=1, h=2, s=256, d=64)
    for causal in (True, False):
        out = flash_attention(q, k, v, causal, None, 128, 128)
        ref = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

        def f_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal, None,
                                           128, 128) ** 2)

        def f_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

        g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4)


def test_pick_block_sizes():
    from ray_tpu.ops.attention import pick_block_sizes

    assert pick_block_sizes(4096, 64) == (512, 1024)
    assert pick_block_sizes(4096, 256) == (256, 256)
    bq, bk = pick_block_sizes(384, 64)
    assert 384 % bq == 0


def test_ring_attention_matches_full_on_8_devices():
    from ray_tpu.ops.ring_attention import ring_attention_sharded
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh

    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    mesh = build_mesh(MeshSpec({"dp": 2, "sp": 4}))
    q, k, v = _qkv(jax.random.PRNGKey(3), b=4, h=2, s=256, d=32)
    out = ring_attention_sharded(q, k, v, mesh, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_ring_attention_non_causal():
    from ray_tpu.ops.ring_attention import ring_attention_sharded
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec({"sp": 8}))
    q, k, v = _qkv(jax.random.PRNGKey(4), b=1, h=2, s=128, d=32)
    out = ring_attention_sharded(q, k, v, mesh, causal=False)
    ref = mha_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_ring_attention_grads_close_to_reference():
    from ray_tpu.ops.ring_attention import ring_attention_sharded
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec({"dp": 2, "sp": 4}))
    q, k, v = _qkv(jax.random.PRNGKey(5), b=2, h=2, s=64, d=32)

    def f_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh, causal=True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-3, rtol=2e-3)
