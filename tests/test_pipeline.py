"""Pipeline parallelism: GPipe schedule over the pp mesh axis.

The pipelined program must be numerically identical to the sequential
layer stack (same math, different schedule), train end-to-end through
jax.grad, and compose with data parallelism on the same mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_tpu.parallel.pipeline import (
    from_microbatches,
    gpipe,
    init_pp_lm,
    make_pp_train_step,
    sequential_forward,
    stack_stage_params,
    stage_shardings,
    to_microbatches,
    unstack_stage_params,
)

VOCAB, D, L, H, FF, S = 128, 32, 8, 4, 64, 16


def _params(n_stages):
    return init_pp_lm(jax.random.PRNGKey(0), VOCAB, D, L, H, FF, S,
                      n_stages=n_stages)


def test_stack_unstack_roundtrip():
    layers = {"w": jnp.arange(24.0).reshape(8, 3)}
    staged = stack_stage_params(layers, 4)
    assert staged["w"].shape == (4, 2, 3)
    np.testing.assert_array_equal(unstack_stage_params(staged)["w"],
                                  layers["w"])
    with pytest.raises(ValueError):
        stack_stage_params(layers, 3)


def test_microbatch_roundtrip():
    x = jnp.arange(32.0).reshape(8, 4)
    mb = to_microbatches(x, 4)
    assert mb.shape == (4, 2, 4)
    np.testing.assert_array_equal(from_microbatches(mb), x)
    with pytest.raises(ValueError):
        to_microbatches(x, 3)


def test_pipelined_forward_matches_sequential():
    mesh = build_mesh(MeshSpec({"dp": 2, "pp": 4}))
    params = _params(4)
    ids = jax.random.randint(jax.random.PRNGKey(1), (8, S), 0, VOCAB,
                             dtype=jnp.int32)
    _, forward = make_pp_train_step(mesh, H, n_microbatches=4,
                                    optimizer=optax.adam(1e-2))
    p_sh = jax.device_put(params, stage_shardings(mesh, params))
    with mesh:
        y_pipe = jax.jit(forward)(p_sh, ids)
    y_seq = sequential_forward(params, ids, H)
    assert float(jnp.max(jnp.abs(y_pipe - y_seq))) < 1e-4


def test_pipelined_training_converges():
    mesh = build_mesh(MeshSpec({"dp": 2, "pp": 4}))
    params = _params(4)
    ids = jax.random.randint(jax.random.PRNGKey(1), (8, S), 0, VOCAB,
                             dtype=jnp.int32)
    opt = optax.adam(1e-2)
    step, _ = make_pp_train_step(mesh, H, n_microbatches=4, optimizer=opt)
    p = jax.device_put(params, stage_shardings(mesh, params))
    o = jax.jit(opt.init)(p)
    batch = {"input_ids": ids, "labels": ids}
    losses = []
    for _ in range(10):
        p, o, loss = step(p, o, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_pipelined_grads_match_sequential():
    """d loss/d params through the pipeline equals the sequential grads."""
    from ray_tpu.models.gpt2 import next_token_loss

    mesh = build_mesh(MeshSpec({"pp": 8}))
    params = _params(8)
    ids = jax.random.randint(jax.random.PRNGKey(2), (4, S), 0, VOCAB,
                             dtype=jnp.int32)
    _, forward = make_pp_train_step(mesh, H, n_microbatches=2,
                                    optimizer=optax.sgd(0.1))
    p_sh = jax.device_put(params, stage_shardings(mesh, params))

    def pipe_loss(p):
        return next_token_loss(forward(p, ids), ids)

    def seq_loss(p):
        return next_token_loss(sequential_forward(p, ids, H), ids)

    with mesh:
        g_pipe = jax.jit(jax.grad(pipe_loss))(p_sh)
    g_seq = jax.grad(seq_loss)(params)
    flat_p, _ = jax.tree.flatten(g_pipe)
    flat_s, _ = jax.tree.flatten(g_seq)
    for a, b in zip(flat_p, flat_s):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-3)


def test_remat_stage_same_result():
    mesh = build_mesh(MeshSpec({"dp": 2, "pp": 4}))
    params = _params(4)
    ids = jax.random.randint(jax.random.PRNGKey(3), (4, S), 0, VOCAB,
                             dtype=jnp.int32)
    opt = optax.sgd(0.1)
    step_a, _ = make_pp_train_step(mesh, H, n_microbatches=2, optimizer=opt)
    step_b, _ = make_pp_train_step(mesh, H, n_microbatches=2, optimizer=opt,
                                   remat_stage=True)
    p = jax.device_put(params, stage_shardings(mesh, params))
    o = jax.jit(opt.init)(p)
    batch = {"input_ids": ids, "labels": ids}
    _, _, loss_a = step_a(p, o, batch)
    _, _, loss_b = step_b(p, o, batch)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
