"""Race-detection harness for the threaded control plane (SURVEY §5.2).

Unit layer: the lock-order witness flags ABBA inversions (even when the
interleaving never actually deadlocks) and stays quiet on consistent
orders. Integration layer: a subprocess runs a real cluster workload —
tasks, actors, waits, puts — with the witness installed before cluster
creation and asserts NO lock-order cycles exist among the control
plane's locks. This is the moral equivalent of the reference's TSAN CI
configs for `src/ray` (bazel --config=tsan): ordering bugs surface from
a single pass, not from winning a rare interleaving.
"""

import os
import subprocess
import sys
import threading

import pytest

from ray_tpu.util import lock_witness

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _ScopedWitness:
    """View of the witness that reports only cycles recorded after its
    creation — lets the unit tests below assert on their own synthetic
    locks without resetting the session-wide graph (a reset would
    destroy edges and cycles recorded by background cluster threads that
    the conftest session gate asserts on)."""

    def __init__(self):
        self._base = len(lock_witness.report().cycles)

    def report(self):
        rep = lock_witness.report()
        return lock_witness.Report(cycles=rep.cycles[self._base:],
                                   locks_tracked=rep.locks_tracked,
                                   edges=rep.edges)

    def __getattr__(self, name):
        return getattr(lock_witness, name)


@pytest.fixture()
def witness():
    session_wide = os.environ.get("RAY_TPU_LOCK_WITNESS") == "1"
    lock_witness.install()
    if not session_wide:
        lock_witness.reset()
        yield lock_witness
        lock_witness.reset()
        lock_witness.uninstall()
        return
    # Session-wide sanitizer run (RAY_TPU_LOCK_WITNESS=1): never touch
    # the global graph. Synthetic locks get fresh witness ids, so they
    # cannot link to pre-existing edges; the scoped view isolates the
    # assertions, and teardown removes exactly the cycles these tests
    # created on purpose (their lock sites name this file) while keeping
    # any real control-plane evidence for the session gate.
    yield _ScopedWitness()
    lock_witness.discard_cycles(os.path.basename(__file__))


def test_witness_flags_abba_inversion(witness):
    a = threading.Lock()
    b = threading.Lock()

    def order_ab():
        with a:
            with b:
                pass

    def order_ba():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=order_ab)
    t1.start()
    t1.join()
    t2 = threading.Thread(target=order_ba)
    t2.start()
    t2.join()
    rep = witness.report()
    assert rep.cycles, "ABBA inversion must be reported"
    assert "lock-order inversion" in rep.cycles[0]


def test_witness_quiet_on_consistent_order(witness):
    a = threading.Lock()
    b = threading.Lock()
    c = threading.Lock()

    def ordered():
        with a:
            with b:
                with c:
                    pass

    threads = [threading.Thread(target=ordered) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert witness.report().cycles == []


def test_witness_mismatched_release_raises(witness):
    """A release by a thread that never recorded the acquire must raise,
    not silently no-op: the silent path left the acquirer's held-stack
    stale, growing phantom order edges that mask real inversions."""
    lock = threading.Lock()
    lock.acquire()
    errors = []

    def rogue_release():
        try:
            lock.release()
        except RuntimeError as e:
            errors.append(e)

    t = threading.Thread(target=rogue_release)
    t.start()
    t.join()
    assert len(errors) == 1
    assert "never acquired" in str(errors[0])
    # The raise must happen BEFORE the inner lock is touched: the lock is
    # still held, and the owning thread can still release it cleanly.
    assert lock.locked()
    lock.release()
    assert not lock.locked()


def test_witness_three_lock_cycle(witness):
    a, b, c = threading.Lock(), threading.Lock(), threading.Lock()
    for first, second in [(a, b), (b, c), (c, a)]:
        def run(x=first, y=second):
            with x:
                with y:
                    pass
        t = threading.Thread(target=run)
        t.start()
        t.join()
    assert witness.report().cycles, "A->B->C->A cycle must be reported"


def test_witness_rlock_and_condition(witness):
    lock = threading.RLock()
    cond = threading.Condition(lock)
    done = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            done.append(True)

    t = threading.Thread(target=waiter)
    t.start()
    import time

    time.sleep(0.2)
    with cond:
        cond.notify_all()
    t.join()
    assert done == [True]
    assert witness.report().cycles == []


_CLUSTER_WORKLOAD = """
import sys
sys.path.insert(0, {repo!r})
from ray_tpu.util import lock_witness
lock_witness.install(watchdog_s=60.0)

import ray_tpu
ray_tpu.init(num_cpus=2)

@ray_tpu.remote
def sq(x):
    return x * x

@ray_tpu.remote
class Acc:
    def __init__(self):
        self.total = 0
    def add(self, v):
        self.total += v
        return self.total

refs = [sq.remote(i) for i in range(40)]
ready, rest = ray_tpu.wait(refs, num_returns=10, timeout=60)
assert len(ready) == 10
vals = ray_tpu.get(refs)
acc = Acc.remote()
outs = ray_tpu.get([acc.add.remote(v) for v in vals[:10]])
big = ray_tpu.put(list(range(100000)))
assert len(ray_tpu.get(big)) == 100000
ray_tpu.shutdown()

rep = lock_witness.report()
print("LOCKS", rep.locks_tracked, "EDGES", rep.edges)
for c in rep.cycles:
    print("CYCLE", c)
print("WITNESS DONE", len(rep.cycles))
"""


def test_control_plane_has_no_lock_order_cycles():
    """Run a real cluster workload under the witness in a fresh
    interpreter (patching must precede lock creation)."""
    proc = subprocess.run(
        [sys.executable, "-c", _CLUSTER_WORKLOAD.format(repo=REPO)],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, RAY_TPU_LOG_LEVEL="WARNING"))
    assert proc.returncode == 0, (proc.stdout[-800:], proc.stderr[-2000:])
    assert "WITNESS DONE 0" in proc.stdout, proc.stdout[-2000:]


_SERVE_WORKLOAD = """
import sys
sys.path.insert(0, {repo!r})
from ray_tpu.util import lock_witness
lock_witness.install(watchdog_s=60.0)

import json
import urllib.request

import ray_tpu
from ray_tpu import serve

ray_tpu.init(num_cpus=4)

@serve.deployment(num_replicas=2, max_concurrent_queries=8)
class Echo:
    def __call__(self, payload):
        return {{"echo": payload}}

handle = serve.run(Echo.bind())
# Handle path (router reserve/release + reaper) and HTTP path (proxy
# light lane + slot ownership) concurrently exercise the serve control
# plane's lock interplay.
refs = [handle.remote(i) for i in range(60)]
port = serve.http_port()
for i in range(20):
    req = urllib.request.Request(
        f"http://127.0.0.1:{{port}}/Echo", data=json.dumps(i).encode(),
        headers={{"Content-Type": "application/json"}})
    with urllib.request.urlopen(req, timeout=60) as resp:
        assert json.loads(resp.read()) == {{"result": {{"echo": i}}}}
assert len(ray_tpu.get(refs)) == 60
serve.shutdown()
ray_tpu.shutdown()

rep = lock_witness.report()
for c in rep.cycles:
    print("CYCLE", c)
print("WITNESS DONE", len(rep.cycles))
"""


def test_serve_control_plane_has_no_lock_order_cycles():
    """The serve stack (controller reconcile, router admission, proxy
    slot ownership, replica streams) under the witness — its lock
    interplay is the densest in the control plane."""
    proc = subprocess.run(
        [sys.executable, "-c", _SERVE_WORKLOAD.format(repo=REPO)],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, RAY_TPU_LOG_LEVEL="WARNING"))
    assert proc.returncode == 0, (proc.stdout[-800:], proc.stderr[-2000:])
    assert "WITNESS DONE 0" in proc.stdout, proc.stdout[-2000:]
