"""raylint (ray_tpu.analysis) tests.

Per-rule fixture pairs: every rule must flag its known-bad snippet and
stay quiet on the known-good twin — the twin is the fix the rule's
message prescribes, so these double as documentation of the discipline.
`test_package_clean` is the tier-1 contract: the engine over `ray_tpu/`
must report zero unsuppressed findings (scripts/gate.sh runs the same
check as its own step, so a regression fails both).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from ray_tpu.analysis import RULES
from ray_tpu.analysis.engine import lint_file

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_src(tmp_path, src, rules=None, name="snippet.py"):
    path = tmp_path / name
    path.write_text(textwrap.dedent(src))
    return lint_file(str(path), rule_ids=rules)


def rule_ids(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------------------ RL001

RL001_BAD = """
    from ray_tpu.core.rpc import DEFERRED

    def handle_fetch(conn, data, executor):
        msg_id = conn.current_msg_id

        def done(result):
            payload = transform(result)
            conn.reply(msg_id, "fetch", payload)

        executor.submit(done)
        return DEFERRED
"""

RL001_GOOD = """
    from ray_tpu.core.rpc import DEFERRED

    def handle_fetch(conn, data, executor):
        msg_id = conn.current_msg_id

        def done(result):
            try:
                conn.reply(msg_id, "fetch", transform(result))
            except Exception as e:
                conn.reply(msg_id, "fetch", {"error": str(e)})

        executor.submit(done)
        return DEFERRED
"""


def test_rl001_flags_unguarded_completion(tmp_path):
    findings = lint_src(tmp_path, RL001_BAD, rules=["RL001"])
    assert rule_ids(findings) == ["RL001"]
    assert "hang" in findings[0].message


def test_rl001_quiet_on_guarded_completion(tmp_path):
    assert lint_src(tmp_path, RL001_GOOD, rules=["RL001"]) == []


RL001_BAD_RAISE_AFTER_PARK = """
    from ray_tpu.core.rpc import DEFERRED

    def handle_take(conn, data, waiters):
        msg_id = conn.current_msg_id
        waiters.append((conn, msg_id))
        if not data.get("key"):
            raise ValueError("missing key")
        return DEFERRED
"""

RL001_GOOD_PARK_LAST = """
    from ray_tpu.core.rpc import DEFERRED

    def handle_take(conn, data, waiters):
        if not data.get("key"):
            raise ValueError("missing key")
        msg_id = conn.current_msg_id
        waiters.append((conn, msg_id))
        return DEFERRED
"""


def test_rl001_flags_raise_after_park(tmp_path):
    findings = lint_src(tmp_path, RL001_BAD_RAISE_AFTER_PARK, rules=["RL001"])
    assert rule_ids(findings) == ["RL001"]
    assert "park" in findings[0].message


def test_rl001_quiet_when_validation_precedes_park(tmp_path):
    assert lint_src(tmp_path, RL001_GOOD_PARK_LAST, rules=["RL001"]) == []


# Serve fast-lane raw-frame idiom (worker._handle_serve_raw): the reply
# is a raw frame sent from an async completion scheduled on the actor
# loop. The completion owns the reply — every exit path must reply_raw
# (user errors travel INSIDE an error frame).

RL001_BAD_SERVE_RAW_FRAME = """
    from ray_tpu.core.rpc import DEFERRED

    def handle_serve_raw(conn, payload, loop, dispatch):
        mid = conn.current_msg_id

        async def run():
            parts = await dispatch(payload)  # a raise strands the caller
            conn.reply_raw(mid, "serve_raw", parts)

        schedule(loop, run())
        return DEFERRED
"""

RL001_GOOD_SERVE_RAW_FRAME = """
    from ray_tpu.core.rpc import DEFERRED

    def handle_serve_raw(conn, payload, loop, dispatch):
        mid = conn.current_msg_id

        async def run():
            try:
                parts = await dispatch(payload)
                conn.reply_raw(mid, "serve_raw", parts)
            except BaseException as e:
                conn.reply_raw(mid, "serve_raw", encode_error_frame(e))

        schedule(loop, run())
        return DEFERRED
"""


def test_rl001_flags_unguarded_raw_frame_completion(tmp_path):
    findings = lint_src(tmp_path, RL001_BAD_SERVE_RAW_FRAME,
                        rules=["RL001"])
    assert rule_ids(findings) == ["RL001"]


def test_rl001_quiet_on_error_frame_guarded_completion(tmp_path):
    assert lint_src(tmp_path, RL001_GOOD_SERVE_RAW_FRAME,
                    rules=["RL001"]) == []


# ------------------------------------------------------------------ RL002

RL002_BAD = """
    import time

    class Manager:
        def tick(self):
            with self._state_lock:
                self._n += 1
                time.sleep(0.5)
"""

RL002_GOOD = """
    import time

    class Manager:
        def tick(self):
            with self._state_lock:
                self._n += 1
            time.sleep(0.5)
"""


def test_rl002_flags_sleep_under_lock(tmp_path):
    findings = lint_src(tmp_path, RL002_BAD, rules=["RL002"])
    assert rule_ids(findings) == ["RL002"]
    assert "_state_lock" in findings[0].message


def test_rl002_quiet_when_blocking_moved_out(tmp_path):
    assert lint_src(tmp_path, RL002_GOOD, rules=["RL002"]) == []


RL002_BAD_RPC = """
    class Controller:
        def checkpoint(self, payload):
            with self._ckpt_lock:
                self._kv().call("kv_put", {"value": payload})
"""


def test_rl002_flags_rpc_with_call_receiver(tmp_path):
    # `self._kv().call(...)` has no dotted name (the receiver is itself a
    # call) — the pre-fix serve controller shape; the rule must still see
    # the `.call` method.
    findings = lint_src(tmp_path, RL002_BAD_RPC, rules=["RL002"])
    assert rule_ids(findings) == ["RL002"]


RL002_GOOD_NESTED_DEF = """
    import time

    class Manager:
        def schedule(self):
            with self._state_lock:
                def later():
                    time.sleep(0.5)
                self._pending.append(later)
"""


def test_rl002_quiet_on_deferred_closure(tmp_path):
    # Code inside a nested def runs when called, not under the lock.
    assert lint_src(tmp_path, RL002_GOOD_NESTED_DEF, rules=["RL002"]) == []


def test_rl002_flags_event_wait_under_lock(tmp_path):
    src = """
        class Manager:
            def drain(self):
                with self._state_lock:
                    self._done_event.wait(30.0)
    """
    findings = lint_src(tmp_path, src, rules=["RL002"])
    assert rule_ids(findings) == ["RL002"]


def test_rl002_quiet_on_condition_wait(tmp_path):
    # Condition.wait holds its own lock by contract and releases it
    # while parked — not a hostage situation.
    src = """
        class Manager:
            def drain(self):
                with self._ckpt_cond:
                    self._ckpt_cond.wait(timeout=1.0)
    """
    assert lint_src(tmp_path, src, rules=["RL002"]) == []


def test_rl002_nested_locks_report_once_innermost(tmp_path):
    # A blocking call under two nested locks is one defect, attributed
    # to the innermost lock — not one finding per enclosing `with`.
    src = """
        import time

        class Manager:
            def drain(self):
                with self._state_lock:
                    with self._io_lock:
                        time.sleep(0.5)
    """
    findings = lint_src(tmp_path, src, rules=["RL002"])
    assert rule_ids(findings) == ["RL002"]
    assert "_io_lock" in findings[0].message


def test_rl002_nested_lock_enter_still_charged_to_outer(tmp_path):
    # Blocking work in the inner with's ENTER expression runs while only
    # the outer lock is held — skipping the inner body must not hide it.
    src = """
        class Manager:
            def drain(self):
                with self._state_lock:
                    with self._kv().call("acquire_lease", {}):
                        pass
    """
    findings = lint_src(tmp_path, src, rules=["RL002"])
    assert rule_ids(findings) == ["RL002"]
    assert "_state_lock" in findings[0].message


def test_rl002_quiet_on_wait_on_the_held_object(tmp_path):
    # Waiting on the very object the `with` holds is the Condition
    # contract even when it is named like a lock (serve/router.py's
    # `self._lock = threading.Condition()`).
    src = """
        class Router:
            def assign(self):
                with self._lock:
                    self._lock.wait(timeout=1.0)
    """
    assert lint_src(tmp_path, src, rules=["RL002"]) == []


# ------------------------------------------------------------------ RL003

RL003_BAD = """
    def broadcast(core, data, peers):
        oid = core.put_raw(data)
        send_all(peers, data)
        core.free_raw(oid)
"""

RL003_GOOD = """
    def broadcast(core, data, peers):
        oid = core.put_raw(data)
        try:
            send_all(peers, data)
        finally:
            core.free_raw(oid)
"""


def test_rl003_flags_free_not_in_finally(tmp_path):
    findings = lint_src(tmp_path, RL003_BAD, rules=["RL003"])
    assert rule_ids(findings) == ["RL003"]
    assert "finally" in findings[0].message


def test_rl003_quiet_on_finally_free(tmp_path):
    assert lint_src(tmp_path, RL003_GOOD, rules=["RL003"]) == []


RL003_GOOD_OWNERSHIP_HANDOFF = """
    def publish(core, data, registry):
        oid = core.put_raw(data)
        registry.register(oid)
"""


def test_rl003_quiet_on_ownership_handoff(tmp_path):
    # Passing the id to another call transfers ownership — the registry
    # frees it; not a leak.
    assert lint_src(tmp_path, RL003_GOOD_OWNERSHIP_HANDOFF,
                    rules=["RL003"]) == []


def test_rl003_quiet_on_handoff_via_assignment(tmp_path):
    # Storing the id into an attribute/container also transfers
    # ownership (whoever owns the structure frees it).
    src = """
        def publish(core, data):
            oid = core.put_raw(data)
            core._pending["k"] = oid
    """
    assert lint_src(tmp_path, src, rules=["RL003"]) == []


# Serve fast-lane flavor: a handler that pins a segment for a raw-frame
# reply must free it on the error paths too — reply_raw raises on a gone
# caller, and the fall-through free then never runs.

RL003_BAD_RAW_REPLY = """
    def handle_serve_chunk(core, conn, frame):
        oid = core.put_raw(frame)
        conn.reply_raw(conn.current_msg_id, "serve_raw", view_of(frame))
        core.free_raw(oid)
"""

RL003_GOOD_RAW_REPLY = """
    def handle_serve_chunk(core, conn, frame):
        oid = core.put_raw(frame)
        try:
            conn.reply_raw(conn.current_msg_id, "serve_raw", view_of(frame))
        finally:
            core.free_raw(oid)
"""


def test_rl003_flags_reply_raw_fall_through_free(tmp_path):
    findings = lint_src(tmp_path, RL003_BAD_RAW_REPLY, rules=["RL003"])
    assert rule_ids(findings) == ["RL003"]
    assert "fall-through" in findings[0].message


def test_rl003_quiet_on_reply_raw_finally_free(tmp_path):
    assert lint_src(tmp_path, RL003_GOOD_RAW_REPLY, rules=["RL003"]) == []


# ------------------------------------------------------------------ RL004

RL004_BAD = """
    def drain(queue):
        try:
            queue.flush()
        except Exception:
            pass
"""

RL004_GOOD = """
    import logging

    logger = logging.getLogger(__name__)

    def drain(queue):
        try:
            queue.flush()
        except Exception:
            logger.warning("flush failed", exc_info=True)
"""


def test_rl004_flags_silent_swallow(tmp_path):
    findings = lint_src(tmp_path, RL004_BAD, rules=["RL004"])
    assert rule_ids(findings) == ["RL004"]


def test_rl004_quiet_when_logged(tmp_path):
    assert lint_src(tmp_path, RL004_GOOD, rules=["RL004"]) == []


def test_rl004_quiet_on_reraise(tmp_path):
    src = """
        def drain(queue):
            try:
                queue.flush()
            except Exception:
                queue.reset()
                raise
    """
    assert lint_src(tmp_path, src, rules=["RL004"]) == []


def test_rl004_honors_noqa_ble001(tmp_path):
    src = """
        def drain(queue):
            try:
                queue.flush()
            except Exception:  # noqa: BLE001 — shutdown is best-effort
                pass
    """
    assert lint_src(tmp_path, src, rules=["RL004"]) == []


# ------------------------------------------------------------------ RL005

RL005_BAD = """
    import threading

    def start(worker):
        t = threading.Thread(target=worker)
        t.start()
"""

RL005_GOOD = """
    import threading

    def start(worker):
        t = threading.Thread(target=worker, daemon=True)
        t.start()
"""

RL005_GOOD_JOINED = """
    import threading

    def run(worker):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
"""


def test_rl005_flags_undaemonized_unjoined_thread(tmp_path):
    findings = lint_src(tmp_path, RL005_BAD, rules=["RL005"])
    assert rule_ids(findings) == ["RL005"]


def test_rl005_quiet_on_daemon(tmp_path):
    assert lint_src(tmp_path, RL005_GOOD, rules=["RL005"]) == []


def test_rl005_quiet_on_join(tmp_path):
    assert lint_src(tmp_path, RL005_GOOD_JOINED, rules=["RL005"]) == []


def test_rl005_flags_explicit_daemon_false(tmp_path):
    # daemon=False is exactly the leak the rule exists to flag; the mere
    # presence of the keyword must not count as compliance.
    src = """
        import threading

        def spawn(fn):
            t = threading.Thread(target=fn, daemon=False)
            t.start()
    """
    findings = lint_src(tmp_path, src, rules=["RL005"])
    assert rule_ids(findings) == ["RL005"]


# ------------------------------------------------------------------ RL020
# (absorbs the retired RL006's lexical fixtures, then the dataflow ones)

RL020_BAD = """
    import jax

    class Engine:
        def decode_step(self, params, tokens):
            fn = jax.jit(self._decode)
            return fn(params, tokens)
"""

RL020_GOOD = """
    import jax

    class Engine:
        def __init__(self):
            self._step = jax.jit(self._decode)

        def decode_step(self, params, tokens):
            return self._step(params, tokens)
"""

RL020_BAD_LOOP = """
    import jax

    def sweep(fns, x):
        outs = []
        for fn in fns:
            outs.append(jax.jit(fn)(x))
        return outs
"""


def test_rl020_flags_jit_in_per_step_method(tmp_path):
    findings = lint_src(tmp_path, RL020_BAD, rules=["RL020"])
    assert rule_ids(findings) == ["RL020"]
    assert "decode_step" in findings[0].message


def test_rl020_quiet_on_factory_scope(tmp_path):
    assert lint_src(tmp_path, RL020_GOOD, rules=["RL020"]) == []


def test_rl020_flags_jit_in_loop(tmp_path):
    findings = lint_src(tmp_path, RL020_BAD_LOOP, rules=["RL020"])
    assert rule_ids(findings) == ["RL020"]
    assert "loop" in findings[0].message


def test_rl020_quiet_on_cached_behind_none_check(tmp_path):
    src = """
        import jax

        class Engine:
            def decode_step(self, params, tokens):
                if self._step is None:
                    self._step = jax.jit(self._decode)
                return self._step(params, tokens)
    """
    assert lint_src(tmp_path, src, rules=["RL020"]) == []


RL020_TRACED_IF = """
    import jax

    @jax.jit
    def step(x):
        if x.sum() > 0:
            return x * 2
        return x
"""

RL020_GOOD_SHAPE_IF = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        if x.shape[0] > 1:
            return jnp.where(x > 0, x * 2, x)
        return x
"""

RL020_HOST_IN_JIT = """
    import jax
    import numpy as np

    @jax.jit
    def step(x):
        return np.asarray(x) + 1
"""

RL020_SHAPE_TO_STATIC = """
    import jax
    import jax.numpy as jnp

    pad = jax.jit(lambda x, n: jnp.pad(x, n), static_argnums=(1,))
    embed = jax.jit(lambda x: x * 2)

    def run(x):
        h = embed(x)
        return pad(h, h.shape[0] * 2)
"""


def test_rl020_flags_python_if_on_traced_value(tmp_path):
    findings = lint_src(tmp_path, RL020_TRACED_IF, rules=["RL020"])
    assert rule_ids(findings) == ["RL020"]
    assert "traced" in findings[0].message


def test_rl020_quiet_on_shape_based_if(tmp_path):
    # x.shape is static at trace time — branching on it is the
    # supported specialize-per-shape idiom, not a hazard.
    assert lint_src(tmp_path, RL020_GOOD_SHAPE_IF, rules=["RL020"]) == []


def test_rl020_flags_host_materialization_inside_jit(tmp_path):
    findings = lint_src(tmp_path, RL020_HOST_IN_JIT, rules=["RL020"])
    assert rule_ids(findings) == ["RL020"]
    assert "materialization" in findings[0].message


def test_rl020_flags_shape_fed_into_static_arg(tmp_path):
    findings = lint_src(tmp_path, RL020_SHAPE_TO_STATIC, rules=["RL020"])
    assert rule_ids(findings) == ["RL020"]
    assert "static" in findings[0].message


def test_rl020_quiet_on_config_static_arg(tmp_path):
    src = """
        import jax
        import jax.numpy as jnp

        pad = jax.jit(lambda x, n: jnp.pad(x, n), static_argnums=(1,))

        def run(x, cfg_n):
            return pad(x, cfg_n)
    """
    assert lint_src(tmp_path, src, rules=["RL020"]) == []


# ------------------------------------------------------------------ RL021

RL021_BAD = """
    import jax

    step = jax.jit(lambda p, t: t)

    class Engine:
        def decode_step(self, params, tokens, reqs):
            nxt = step(params, tokens)
            for r in reqs:
                r.out.append(int(nxt[r.slot]))
"""

RL021_GOOD = """
    import jax
    import numpy as np

    step = jax.jit(lambda p, t: t)

    class Engine:
        def decode_step(self, params, tokens, reqs):
            nxt = step(params, tokens)
            host = np.asarray(nxt)
            for r in reqs:
                r.out.append(int(host[r.slot]))
"""


def test_rl021_flags_device_sync_in_hot_loop(tmp_path):
    findings = lint_src(tmp_path, RL021_BAD, rules=["RL021"])
    assert rule_ids(findings) == ["RL021"]
    assert "decode_step" in findings[0].message


def test_rl021_quiet_on_hoisted_post_step_sync(tmp_path):
    # The engine idiom: ONE np.asarray before the loop, the loop reads
    # the host copy — provenance keeps this silent where a lexical rule
    # would flag the int() calls.
    assert lint_src(tmp_path, RL021_GOOD, rules=["RL021"]) == []


def test_rl021_quiet_in_cold_methods(tmp_path):
    # Same sync-in-loop shape, but not a per-step method: checkpoint
    # save paths may sync per tensor.
    src = """
        import jax

        step = jax.jit(lambda p, t: t)

        class Engine:
            def save_checkpoint(self, params, tokens, reqs):
                nxt = step(params, tokens)
                for r in reqs:
                    r.out.append(int(nxt[r.slot]))
    """
    assert lint_src(tmp_path, src, rules=["RL021"]) == []


# ------------------------------------------------------------------ RL022

RL022_BAD = """
    import jax

    decode = jax.jit(lambda params, arena: (1, arena),
                     donate_argnums=(1,))

    class Engine:
        def run(self, params):
            out, _ = decode(params, self._arena)
            return self._arena
"""

RL022_GOOD = """
    import jax

    decode = jax.jit(lambda params, arena: (1, arena),
                     donate_argnums=(1,))

    class Engine:
        def run(self, params):
            nxt, self._arena = decode(params, self._arena)
            return nxt
"""


def test_rl022_flags_read_after_donate(tmp_path):
    findings = lint_src(tmp_path, RL022_BAD, rules=["RL022"])
    assert rule_ids(findings) == ["RL022"]
    assert "donate" in findings[0].message


def test_rl022_quiet_on_rebind_from_result(tmp_path):
    assert lint_src(tmp_path, RL022_GOOD, rules=["RL022"]) == []


def test_rl022_flags_read_on_one_cfg_branch(tmp_path):
    src = """
        import jax

        decode = jax.jit(lambda params, arena: (1, arena),
                         donate_argnums=(1,))

        class Engine:
            def run(self, params, flaky):
                nxt, arenas = decode(params, self._arenas)
                if flaky:
                    return self._arenas
                self._arenas = arenas
                return nxt
    """
    findings = lint_src(tmp_path, src, rules=["RL022"])
    assert rule_ids(findings) == ["RL022"]


def test_rl022_quiet_when_rebuilt_before_read(tmp_path):
    # The engine's fail_all path: the arenas are rebuilt from scratch
    # before anything reads them again.
    src = """
        import jax

        decode = jax.jit(lambda params, arena: (1, arena),
                         donate_argnums=(1,))

        class Engine:
            def run(self, params):
                out, _ = decode(params, self._arenas)
                self._arenas = self._build_arenas()
                return self._arenas
    """
    assert lint_src(tmp_path, src, rules=["RL022"]) == []


# ------------------------------------------------------------------ RL024

RL024_BAD = """
    import jax

    class Model:
        def build(self):
            def fwd(x):
                return x * self._scale
            self._fn = jax.jit(fwd)

        def set_scale(self, s):
            self._scale = s
"""

RL024_GOOD = """
    import jax

    class Model:
        def build(self):
            def fwd(x, scale):
                return x * scale
            self._fn = jax.jit(fwd)

        def set_scale(self, s):
            self._scale = s
"""


def test_rl024_flags_jitted_closure_over_mutable_attr(tmp_path):
    findings = lint_src(tmp_path, RL024_BAD, rules=["RL024"])
    assert rule_ids(findings) == ["RL024"]
    assert "_scale" in findings[0].message
    assert "set_scale" in findings[0].message


def test_rl024_quiet_when_value_is_an_argument(tmp_path):
    assert lint_src(tmp_path, RL024_GOOD, rules=["RL024"]) == []


def test_rl024_quiet_when_attr_only_set_in_init(tmp_path):
    src = """
        import jax

        class Model:
            def __init__(self, scale):
                self._scale = scale
                def fwd(x):
                    return x * self._scale
                self._fn = jax.jit(fwd)
    """
    assert lint_src(tmp_path, src, rules=["RL024"]) == []


# ------------------------------------------------------------------ RL007

RL007_BAD = """
    import threading

    class Pair:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def forward(self):
            with self._a_lock:
                with self._b_lock:
                    pass

        def backward(self):
            with self._b_lock:
                with self._a_lock:
                    pass
"""

RL007_GOOD = """
    import threading

    class Pair:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def forward(self):
            with self._a_lock:
                with self._b_lock:
                    pass

        def backward(self):
            with self._a_lock:
                with self._b_lock:
                    pass
"""


def test_rl007_flags_abba_order_cycle(tmp_path):
    findings = lint_src(tmp_path, RL007_BAD, rules=["RL007"])
    assert rule_ids(findings) == ["RL007"]
    assert "cycle" in findings[0].message


def test_rl007_quiet_on_consistent_order(tmp_path):
    assert lint_src(tmp_path, RL007_GOOD, rules=["RL007"]) == []


def test_rl007_flags_self_deadlock_through_method_call(tmp_path):
    src = """
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()

            def delete(self, key):
                with self._lock:
                    self._evict(key)

            def _evict(self, key):
                with self._lock:
                    pass
    """
    findings = lint_src(tmp_path, src, rules=["RL007"])
    assert rule_ids(findings) == ["RL007"]
    assert "re-acquisition" in findings[0].message


def test_rl007_quiet_on_rlock_reentry(tmp_path):
    src = """
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.RLock()

            def delete(self, key):
                with self._lock:
                    self._evict(key)

            def _evict(self, key):
                with self._lock:
                    pass
    """
    assert lint_src(tmp_path, src, rules=["RL007"]) == []


# ----------------------------------------------------------- suppressions


def test_line_suppression(tmp_path):
    src = """
        import time

        class Manager:
            def tick(self):
                with self._state_lock:
                    time.sleep(0.5)  # raylint: disable=RL002
    """
    assert lint_src(tmp_path, src, rules=["RL002"]) == []


def test_suppression_comment_on_line_above(tmp_path):
    src = """
        import time

        class Manager:
            def tick(self):
                with self._state_lock:
                    # raylint: disable=RL002
                    time.sleep(0.5)
    """
    assert lint_src(tmp_path, src, rules=["RL002"]) == []


def test_trailing_suppression_does_not_leak_to_next_line(tmp_path):
    # The line-above form is for COMMENT-ONLY marker lines; a trailing
    # marker on the previous code line must not silently suppress an
    # unannotated violation directly below it.
    src = """
        import time

        class Manager:
            def tick(self):
                with self._state_lock:
                    time.sleep(0.5)  # raylint: disable=RL002
                    time.sleep(0.5)
    """
    findings = lint_src(tmp_path, src, rules=["RL002"])
    assert rule_ids(findings) == ["RL002"]
    assert findings[0].line == 8


def test_file_wide_suppression(tmp_path):
    src = """
        # raylint: disable-file=RL002
        import time

        class Manager:
            def tick(self):
                with self._state_lock:
                    time.sleep(0.5)
    """
    assert lint_src(tmp_path, src, rules=["RL002"]) == []


def test_suppression_is_rule_scoped(tmp_path):
    # Disabling one rule must not blanket others on the same line.
    src = """
        import time

        class Manager:
            def tick(self):
                with self._state_lock:
                    time.sleep(0.5)  # raylint: disable=RL004
    """
    findings = lint_src(tmp_path, src, rules=["RL002"])
    assert rule_ids(findings) == ["RL002"]


# ------------------------------------------------------------------- CLI


def test_cli_json_output_and_exit_code(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(RL002_BAD))
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.analysis", str(bad), "--json"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert [f["rule"] for f in payload] == ["RL002"]
    assert payload[0]["line"] > 0


def test_cli_exit_zero_on_clean_file(tmp_path):
    good = tmp_path / "good.py"
    good.write_text(textwrap.dedent(RL002_GOOD))
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.analysis", str(good)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_syntax_error_is_a_finding(tmp_path):
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.analysis", str(broken)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 1
    assert "RL000" in proc.stdout


def test_sleep_report_accounts_loops(tmp_path):
    src = """
        import time

        def test_poll():
            for _ in range(20):
                time.sleep(1.0)

        def test_quick():
            time.sleep(0.1)
    """
    mod = tmp_path / "sleepy.py"
    mod.write_text(textwrap.dedent(src))
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.analysis", "--sleep-report",
         "--json", str(mod)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0
    rows = {r["function"]: r["sleep_s"] for r in json.loads(proc.stdout)}
    assert rows["test_poll"] == pytest.approx(20.0)
    assert rows["test_quick"] == pytest.approx(0.1)


def test_sleep_report_counts_nonliteral_loop_bounds_once(tmp_path):
    # A named bound must count the loop once (under-estimate), not
    # multiply by zero and erase the sleep from the audit entirely.
    src = """
        import time

        N = 30

        def test_named_bound_poll():
            for _ in range(N):
                time.sleep(0.5)
    """
    mod = tmp_path / "named_bound.py"
    mod.write_text(textwrap.dedent(src))
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.analysis", "--sleep-report",
         "--json", str(mod)],
        capture_output=True, text=True, cwd=REPO)
    rows = {r["function"]: r["sleep_s"] for r in json.loads(proc.stdout)}
    assert rows["test_named_bound_poll"] == pytest.approx(0.5)


# ------------------------------------------------------------- the gate


def test_every_rule_has_fixture_coverage():
    # Engine-level guard: a new rule must come with fixture tests. This
    # module (or the project-rule suite next door) names every rule id
    # in some RLxxx fixture constant/test.
    from ray_tpu.analysis import PROJECT_RULES

    body = ""
    for fname in (os.path.abspath(__file__),
                  os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "test_raylint_project.py")):
        with open(fname, "r", encoding="utf-8") as f:
            body += f.read()
    for rid in list(RULES) + list(PROJECT_RULES):
        assert rid in body, f"rule {rid} has no fixture test here"


def test_package_clean():
    """Tier-1 contract: zero unsuppressed findings over ray_tpu/."""
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.analysis", "ray_tpu/"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, (
        "raylint found regressions:\n" + proc.stdout + proc.stderr)


# ------------------------------------------------------------------ RL008

RL008_BAD_DISCARDED = """
    def serve_request(tracer, payload):
        tracer.start_span("serve.request")
        return handle(payload)
"""

RL008_BAD_NO_FINALLY = """
    def serve_request(tracer, payload):
        span = tracer.start_span("serve.request")
        result = handle(payload)
        span.end()
        return result
"""

RL008_GOOD_WITH = """
    def serve_request(tracer, payload):
        with tracer.start_span("serve.request") as span:
            span.set_attr("size", len(payload))
            return handle(payload)
"""

RL008_GOOD_FINALLY = """
    def serve_request(tracer, payload):
        span = tracer.start_span("serve.request")
        try:
            return handle(payload)
        finally:
            span.end()
"""

RL008_BAD_CHAINED = """
    def serve_request(payload):
        get_tracer().start_span("serve.request")
        return handle(payload)
"""

RL008_GOOD_GUARDED_ASSIGN = """
    def serve_request(payload):
        span = NOOP_SPAN
        if ENABLED:
            span = get_tracer().start_span("serve.request")
        with span:
            return handle(payload)
"""


def test_rl008_flags_discarded_span(tmp_path):
    findings = lint_src(tmp_path, RL008_BAD_DISCARDED, rules=["RL008"])
    assert rule_ids(findings) == ["RL008"]


def test_rl008_sees_chained_receiver_call_shape(tmp_path):
    # `get_tracer().start_span(...)` has no dotted name (the receiver is
    # itself a call) — the rule must match on the attribute shape, or
    # the dominant production form would be invisible.
    findings = lint_src(tmp_path, RL008_BAD_CHAINED, rules=["RL008"])
    assert rule_ids(findings) == ["RL008"]


def test_rl008_quiet_on_guarded_assign_then_with(tmp_path):
    # The instrumentation idiom: NOOP default, conditional real span,
    # one `with span:` entering whichever it is.
    assert lint_src(tmp_path, RL008_GOOD_GUARDED_ASSIGN,
                    rules=["RL008"]) == []


def test_rl008_flags_end_outside_finally(tmp_path):
    # A straight-line span.end() is skipped whenever handle() raises:
    # the trace context never resets and the span never records.
    findings = lint_src(tmp_path, RL008_BAD_NO_FINALLY, rules=["RL008"])
    assert rule_ids(findings) == ["RL008"]


def test_rl008_quiet_on_context_manager(tmp_path):
    assert lint_src(tmp_path, RL008_GOOD_WITH, rules=["RL008"]) == []


def test_rl008_quiet_on_finally_end(tmp_path):
    assert lint_src(tmp_path, RL008_GOOD_FINALLY, rules=["RL008"]) == []


def test_rl008_suppression_for_factories(tmp_path):
    src = """
    def make_span(tracer, name):
        return tracer.start_span(name)  # raylint: disable=RL008
    """
    assert lint_src(tmp_path, src, rules=["RL008"]) == []


# ------------------------------------------------------------------ RL009

RL009_BAD_NAKED_GANG = """
    import ray_tpu
    from ray_tpu.util.placement_group import placement_group
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )

    def spawn_gang(cls, n):
        pg = placement_group([{"CPU": 1}] * n)
        handles = []
        for rank in range(n):
            strategy = PlacementGroupSchedulingStrategy(
                pg, placement_group_bundle_index=rank)
            handles.append(ray_tpu.remote(cls).options(
                scheduling_strategy=strategy).remote(rank))
        return handles
"""

RL009_BAD_ABORT_ONLY = """
    import ray_tpu
    from ray_tpu.util.placement_group import placement_group, \\
        remove_placement_group
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )

    def spawn_gang(cls, n):
        pg = placement_group([{"CPU": 1}] * n)
        handles = []
        try:
            for rank in range(n):
                handles.append(ray_tpu.remote(cls).options(
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        pg, placement_group_bundle_index=rank)).remote(rank))
        except Exception:
            for h in handles:
                ray_tpu.kill(h)
            remove_placement_group(pg)
            raise
        return handles
"""

RL009_GOOD_FULL_DISCIPLINE = """
    import ray_tpu
    from ray_tpu.shardgroup import GangMonitor, ReplicaGroup, ShardSpec
    from ray_tpu.util.placement_group import placement_group, \\
        remove_placement_group
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )

    def spawn_gang(cls, n, on_death):
        pg = placement_group([{"CPU": 1}] * n)
        handles = []
        try:
            for rank in range(n):
                handles.append(ray_tpu.remote(cls).options(
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        pg, placement_group_bundle_index=rank)).remote(rank))
        except Exception:
            for h in handles:
                ray_tpu.kill(h)
            remove_placement_group(pg)
            raise
        group = ReplicaGroup("g", ShardSpec(world_size=n), pg, handles,
                             [str(r) for r in range(n)])
        GangMonitor(group, on_death)
        return group
"""

RL009_GOOD_SINGLE_ACTOR = """
    import ray_tpu
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )

    def spawn_one(cls, pg):
        # One actor on a PG is not a gang — no loop, no RL009.
        return ray_tpu.remote(cls).options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                pg, placement_group_bundle_index=0)).remote()

    def submit_many(handles, payloads):
        # Loops of .remote() WITHOUT a strategy construction are calls,
        # not gang creation.
        return [h.run.remote(p) for h, p in zip(handles, payloads)]
"""


def test_rl009_flags_naked_gang(tmp_path):
    findings = lint_src(tmp_path, RL009_BAD_NAKED_GANG, rules=["RL009"])
    assert rule_ids(findings) == ["RL009"]
    assert "abort" in findings[0].message
    assert "death hook" in findings[0].message


def test_rl009_flags_abort_without_death_hook(tmp_path):
    findings = lint_src(tmp_path, RL009_BAD_ABORT_ONLY, rules=["RL009"])
    assert rule_ids(findings) == ["RL009"]
    assert "death hook" in findings[0].message
    assert "abort" not in findings[0].message.split(";")[0] or \
        "no abort" not in findings[0].message


def test_rl009_quiet_on_full_discipline(tmp_path):
    assert lint_src(tmp_path, RL009_GOOD_FULL_DISCIPLINE,
                    rules=["RL009"]) == []


def test_rl009_quiet_on_non_gang_shapes(tmp_path):
    assert lint_src(tmp_path, RL009_GOOD_SINGLE_ACTOR,
                    rules=["RL009"]) == []


def test_rl009_suppression(tmp_path):
    src = RL009_BAD_NAKED_GANG.replace(
        "for rank in range(n):",
        "for rank in range(n):  # raylint: disable=RL009")
    assert lint_src(tmp_path, src, rules=["RL009"]) == []


RL009_BAD_OPTIONS_CHAIN = """
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )

    def spawn_gang(actor_cls, pg, n):
        # The dominant real shape: `.remote()` hangs off an options()
        # CALL, so it has no dotted name — must still count as a gang.
        handles = []
        for rank in range(n):
            handles.append(actor_cls.options(
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    pg, placement_group_bundle_index=rank)).remote(rank))
        return handles
"""


def test_rl009_flags_options_chain_gang(tmp_path):
    findings = lint_src(tmp_path, RL009_BAD_OPTIONS_CHAIN, rules=["RL009"])
    assert rule_ids(findings) == ["RL009"]


# ------------------------------------------------------------------ RL010

RL010_BAD_POLL = """
    import time

    def wait_for_peer(peer):
        while True:
            if peer.alive():
                return True
            time.sleep(0.1)
"""

RL010_BAD_EVENT_POLL = """
    def drain(queue_obj, ev):
        while True:
            if queue_obj.empty():
                ev.wait(0.5)
                continue
            queue_obj.pop()
"""

RL010_GOOD_DEADLINE = """
    import time

    def wait_for_peer(peer, deadline_s=30.0):
        deadline = time.monotonic() + deadline_s
        while True:
            if peer.alive():
                return True
            if time.monotonic() > deadline:
                raise TimeoutError("peer never came up")
            time.sleep(0.1)
"""

RL010_GOOD_ATTEMPTS = """
    import time

    def call_with_retries(fn, max_attempts=5):
        attempts = 0
        while True:
            try:
                return fn()
            except ConnectionError:
                attempts += 1
                if attempts >= max_attempts:
                    raise
                time.sleep(0.1)
"""

RL010_GOOD_SERVICE_LOOP = """
    def heartbeat_loop(self):
        # Event-conditioned service loop: the stop signal is the bound.
        while not self._stopped.wait(1.0):
            self.send_heartbeat()
"""

RL010_GOOD_KEEPALIVE = """
    import time

    def daemon_main():
        while True:  # woken only by signals
            time.sleep(3600)
"""

RL010_GOOD_TIMEOUT_KWARG = """
    import time

    def pump(refs, runtime):
        while True:
            ready = runtime.wait(refs, timeout=30.0)
            if not refs:
                return
            time.sleep(0.01)
"""


def test_rl010_flags_unbounded_poll(tmp_path):
    findings = lint_src(tmp_path, RL010_BAD_POLL, rules=["RL010"])
    assert rule_ids(findings) == ["RL010"]
    assert "deadline" in findings[0].message


def test_rl010_flags_event_poll(tmp_path):
    findings = lint_src(tmp_path, RL010_BAD_EVENT_POLL, rules=["RL010"])
    assert rule_ids(findings) == ["RL010"]


def test_rl010_quiet_on_deadline(tmp_path):
    assert lint_src(tmp_path, RL010_GOOD_DEADLINE, rules=["RL010"]) == []


def test_rl010_quiet_on_attempt_bound(tmp_path):
    assert lint_src(tmp_path, RL010_GOOD_ATTEMPTS, rules=["RL010"]) == []


def test_rl010_quiet_on_service_loop(tmp_path):
    assert lint_src(tmp_path, RL010_GOOD_SERVICE_LOOP, rules=["RL010"]) == []


def test_rl010_quiet_on_signal_keepalive(tmp_path):
    assert lint_src(tmp_path, RL010_GOOD_KEEPALIVE, rules=["RL010"]) == []


def test_rl010_timeout_kwarg_is_bound_evidence(tmp_path):
    assert lint_src(tmp_path, RL010_GOOD_TIMEOUT_KWARG,
                    rules=["RL010"]) == []


def test_rl010_suppression(tmp_path):
    src = RL010_BAD_POLL.replace(
        "while True:",
        "while True:  # raylint: disable=RL010")
    assert lint_src(tmp_path, src, rules=["RL010"]) == []


# ------------------------------------------------------------------ RL011

RL011_BAD_NO_EVICTION = """
    class TenantRegistry:
        def __init__(self):
            self._buckets = {}

        def admit(self, tenant):
            self._buckets[tenant] = self._buckets.get(tenant, 0) + 1
"""

RL011_GOOD_PRUNE = """
    class TenantRegistry:
        def __init__(self):
            self._buckets = {}

        def admit(self, tenant):
            self._buckets[tenant] = self._buckets.get(tenant, 0) + 1

        def prune(self, live):
            for name in list(self._buckets):
                if name not in live:
                    self._buckets.pop(name, None)
"""

RL011_GOOD_DEL = """
    class AdapterBank:
        def __init__(self):
            self._rows = {}

        def load(self, model_id, row):
            self._rows[model_id] = row

        def evict(self, model_id):
            del self._rows[model_id]
"""

RL011_GOOD_CONSTANT_KEYS = """
    class Counters:
        def __init__(self):
            self._c = {}

        def on_hit(self):
            # Fixed key space: cannot grow under churn.
            self._c["hits"] = self._c.get("hits", 0) + 1
"""

RL011_GOOD_REASSIGNED = """
    class Snapshot:
        def __init__(self):
            self._view = {}

        def update(self, key, value):
            self._view[key] = value

        def refresh(self, table):
            self._view = dict(table)   # rebuilt wholesale: bounded
"""

RL011_GOOD_HANDOFF = """
    class Router:
        def __init__(self):
            self._inflight = {}

        def reserve(self, rid):
            self._inflight[rid] = 1

        def sweep(self):
            prune_against_table(self._inflight)
"""


def test_rl011_flags_keyed_dict_without_eviction(tmp_path):
    findings = lint_src(tmp_path, RL011_BAD_NO_EVICTION, rules=["RL011"])
    assert rule_ids(findings) == ["RL011"]
    assert "_buckets" in findings[0].message
    assert "churn" in findings[0].message


def test_rl011_quiet_with_prune_pop(tmp_path):
    assert lint_src(tmp_path, RL011_GOOD_PRUNE, rules=["RL011"]) == []


def test_rl011_quiet_with_del(tmp_path):
    assert lint_src(tmp_path, RL011_GOOD_DEL, rules=["RL011"]) == []


def test_rl011_quiet_on_constant_keys(tmp_path):
    assert lint_src(tmp_path, RL011_GOOD_CONSTANT_KEYS,
                    rules=["RL011"]) == []


def test_rl011_quiet_on_wholesale_reassignment(tmp_path):
    assert lint_src(tmp_path, RL011_GOOD_REASSIGNED, rules=["RL011"]) == []


def test_rl011_quiet_on_bare_handoff(tmp_path):
    assert lint_src(tmp_path, RL011_GOOD_HANDOFF, rules=["RL011"]) == []


def test_rl011_suppression_with_reason(tmp_path):
    src = RL011_BAD_NO_EVICTION.replace(
        "self._buckets[tenant] = self._buckets.get(tenant, 0) + 1",
        "self._buckets[tenant] = 1  "
        "# raylint: disable=RL011 — bounded by the fixed tenant set")
    assert lint_src(tmp_path, src, rules=["RL011"]) == []

# ------------------------------------------------------------------ RL012

RL012_BAD_NO_INVALIDATION = """
    class Transport:
        def __init__(self):
            self._leases = {}

        def on_grant(self, key, lease):
            self._leases[key] = lease

        def pick(self, key):
            return self._leases.get(key)
"""

RL012_BAD_SHUTDOWN_ONLY = """
    class Transport:
        def __init__(self):
            self._peer_clients = {}

        def dial(self, addr, client):
            self._peer_clients[addr] = client

        def close(self):
            self._peer_clients.clear()
"""

RL012_GOOD_DEATH_HOOK = """
    class Transport:
        def __init__(self):
            self._leases = {}

        def on_grant(self, key, lease):
            self._leases[key] = lease

        def _on_worker_lost(self, key):
            self._leases.pop(key, None)
"""

RL012_GOOD_LIVENESS_SWEEP = """
    class Transport:
        def __init__(self):
            self._peer_clients = {}

        def dial(self, addr, client):
            self._peer_clients[addr] = client

        def _sweep_clients(self):
            for addr in list(self._peer_clients):
                if self._peer_clients[addr].is_closed:
                    self._peer_clients.pop(addr)
"""

RL012_GOOD_ALIAS_REMOVAL = """
    class Transport:
        def __init__(self):
            self._leases = {}

        def on_grant(self, key, lease):
            self._leases[key] = lease

        def _on_worker_lost(self, key, lease):
            leases = self._leases.get(key)
            if leases is not None:
                leases.remove(lease)
"""

RL012_GOOD_NON_ADDRESS_NAME = """
    class Counter:
        def __init__(self):
            self._totals = {}

        def bump(self, key):
            self._totals[key] = self._totals.get(key, 0) + 1
"""


def test_rl012_flags_cache_without_invalidation(tmp_path):
    findings = lint_src(tmp_path, RL012_BAD_NO_INVALIDATION,
                        rules=["RL012"])
    assert rule_ids(findings) == ["RL012"]
    assert "_leases" in findings[0].message
    assert "stale" in findings[0].message


def test_rl012_flags_shutdown_only_cleanup(tmp_path):
    findings = lint_src(tmp_path, RL012_BAD_SHUTDOWN_ONLY,
                        rules=["RL012"])
    assert rule_ids(findings) == ["RL012"]
    assert "shutdown" in findings[0].message


def test_rl012_quiet_with_death_hook(tmp_path):
    assert lint_src(tmp_path, RL012_GOOD_DEATH_HOOK,
                    rules=["RL012"]) == []


def test_rl012_quiet_with_liveness_sweep(tmp_path):
    assert lint_src(tmp_path, RL012_GOOD_LIVENESS_SWEEP,
                    rules=["RL012"]) == []


def test_rl012_quiet_on_alias_removal_in_death_hook(tmp_path):
    assert lint_src(tmp_path, RL012_GOOD_ALIAS_REMOVAL,
                    rules=["RL012"]) == []


def test_rl012_ignores_non_address_caches(tmp_path):
    # RL012 is scoped to worker/lease identity caches by name; a plain
    # counter dict is RL011's business, not RL012's.
    assert lint_src(tmp_path, RL012_GOOD_NON_ADDRESS_NAME,
                    rules=["RL012"]) == []


def test_rl012_suppression_with_reason(tmp_path):
    src = RL012_BAD_NO_INVALIDATION.replace(
        "self._leases[key] = lease",
        "self._leases[key] = lease  "
        "# raylint: disable=RL012 — entries rebuilt on every read")
    assert lint_src(tmp_path, src, rules=["RL012"]) == []


# ------------------------------------------------------------------ RL013

RL013_BAD_NO_BOUND = """
    class WindowBuffer:
        def __init__(self):
            self._blocks = []

        def on_block(self, block):
            self._blocks.append(block)
"""

RL013_BAD_DICT_BUFFER = """
    class PartitionAccumulator:
        def __init__(self):
            self._by_partition = {}

        def scatter(self, part, block):
            self._by_partition.setdefault(part, []).append(block)
"""

RL013_GOOD_BUDGET_ACQUIRE = """
    class WindowBuffer:
        def __init__(self, budget):
            self._budget = budget
            self._blocks = []

        def on_block(self, block, nbytes):
            self._budget.acquire("window", nbytes)
            self._blocks.append(block)
"""

RL013_GOOD_BOUND_CHECK = """
    class WindowBuffer:
        def __init__(self):
            self._blocks = []
            self._max_buffered = 16

        def on_block(self, block):
            if len(self._blocks) >= self._max_buffered:
                raise BufferError("window full")
            self._blocks.append(block)
"""

RL013_GOOD_DRAIN = """
    class WindowBuffer:
        def __init__(self):
            self._blocks = []

        def on_block(self, block):
            self._blocks.append(block)

        def drain(self):
            while self._blocks:
                yield self._blocks.pop()
"""

RL013_GOOD_DEQUE_MAXLEN = """
    from collections import deque

    class WindowBuffer:
        def __init__(self):
            self._blocks = deque(maxlen=8)

        def on_block(self, block):
            self._blocks.append(block)
"""


def test_rl013_flags_unbounded_list_buffer(tmp_path):
    findings = lint_src(tmp_path, RL013_BAD_NO_BOUND, rules=["RL013"])
    assert rule_ids(findings) == ["RL013"]
    assert "_blocks" in findings[0].message
    assert "budget" in findings[0].message.lower()


def test_rl013_flags_keyed_dict_buffer(tmp_path):
    findings = lint_src(tmp_path, RL013_BAD_DICT_BUFFER, rules=["RL013"])
    assert rule_ids(findings) == ["RL013"]
    assert "_by_partition" in findings[0].message


def test_rl013_quiet_with_budget_acquire(tmp_path):
    assert lint_src(tmp_path, RL013_GOOD_BUDGET_ACQUIRE,
                    rules=["RL013"]) == []


def test_rl013_quiet_with_bound_check(tmp_path):
    assert lint_src(tmp_path, RL013_GOOD_BOUND_CHECK,
                    rules=["RL013"]) == []


def test_rl013_quiet_with_drain_path(tmp_path):
    assert lint_src(tmp_path, RL013_GOOD_DRAIN, rules=["RL013"]) == []


def test_rl013_quiet_on_bounded_deque(tmp_path):
    assert lint_src(tmp_path, RL013_GOOD_DEQUE_MAXLEN,
                    rules=["RL013"]) == []


def test_rl013_suppression_with_reason(tmp_path):
    src = RL013_BAD_NO_BOUND.replace(
        "self._blocks.append(block)",
        "self._blocks.append(block)  "
        "# raylint: disable=RL013 — producer enforces the window budget")
    assert lint_src(tmp_path, src, rules=["RL013"]) == []


def test_rl013_scoped_to_data_package(tmp_path):
    # The same shape inside a ray_tpu control-plane package is RL011's
    # business; RL013 only patrols the data plane (and fixtures).
    pkg = tmp_path / "ray_tpu"
    serve = pkg / "serve"
    serve.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (serve / "__init__.py").write_text("")
    mod = serve / "router.py"
    mod.write_text(textwrap.dedent(RL013_BAD_NO_BOUND))
    assert lint_file(str(mod), rule_ids=["RL013"]) == []


# ------------------------------------------------------------------ RL018

RL018_BAD_NO_TEARDOWN = """
    class Admission:
        def __init__(self):
            self._jobs = {}

        def register(self, job_hex, qos):
            self._jobs[job_hex] = qos
"""

# Eviction exists, but on a path with no teardown shape: RL011 would be
# satisfied, RL018 is not — job state must die on the job-finished path,
# not wherever an unrelated refresh happens to run.
RL018_BAD_EVICTION_OFF_TEARDOWN = """
    class Admission:
        def __init__(self):
            self._jobs = {}

        def register(self, job_hex, qos):
            self._jobs[job_hex] = qos

        def refresh(self, job_hex):
            self._jobs.pop(job_hex, None)
"""

RL018_GOOD_UNREGISTER = """
    class Admission:
        def __init__(self):
            self._jobs = {}

        def register(self, job_hex, qos):
            self._jobs[job_hex] = qos

        def unregister(self, job_hex):
            self._jobs.pop(job_hex, None)
"""

RL018_GOOD_SWEEP_REASSIGN = """
    class Reaper:
        def __init__(self):
            self._finished_jobs = {}

        def note(self, job_hex, ts):
            self._finished_jobs[job_hex] = ts

        def _sweep_finished_jobs(self, now):
            self._finished_jobs = {h: t for h, t
                                   in self._finished_jobs.items()
                                   if now - t < 60.0}
"""

RL018_GOOD_NON_JOB_KEYS = """
    class Router:
        def __init__(self):
            self._routes = {}

        def learn(self, replica, addr):
            self._routes[replica] = addr
"""


def test_rl018_flags_job_keyed_dict_without_teardown(tmp_path):
    findings = lint_src(tmp_path, RL018_BAD_NO_TEARDOWN, rules=["RL018"])
    assert rule_ids(findings) == ["RL018"]
    assert "_jobs" in findings[0].message
    assert "die with its job" in findings[0].message


def test_rl018_flags_eviction_off_the_teardown_path(tmp_path):
    findings = lint_src(tmp_path, RL018_BAD_EVICTION_OFF_TEARDOWN,
                        rules=["RL018"])
    assert rule_ids(findings) == ["RL018"]
    # ...while RL011 is satisfied by the same snippet: the rules are
    # answering different questions.
    assert lint_src(tmp_path, RL018_BAD_EVICTION_OFF_TEARDOWN,
                    rules=["RL011"]) == []


def test_rl018_quiet_with_unregister_pop(tmp_path):
    assert lint_src(tmp_path, RL018_GOOD_UNREGISTER, rules=["RL018"]) == []


def test_rl018_quiet_with_sweep_reassignment(tmp_path):
    assert lint_src(tmp_path, RL018_GOOD_SWEEP_REASSIGN,
                    rules=["RL018"]) == []


def test_rl018_quiet_on_non_job_keys(tmp_path):
    assert lint_src(tmp_path, RL018_GOOD_NON_JOB_KEYS,
                    rules=["RL018"]) == []


def test_rl018_suppression_with_reason(tmp_path):
    src = RL018_BAD_NO_TEARDOWN.replace(
        "self._jobs[job_hex] = qos",
        "self._jobs[job_hex] = qos  "
        "# raylint: disable=RL018 — retained as the job history table")
    assert lint_src(tmp_path, src, rules=["RL018"]) == []


# ------------------------------------------------------------------ RL019

RL019_BAD_LIST_OVER_ROWS = """
    def collect(ds):
        return list(ds.iter_rows())
"""

RL019_BAD_SORTED_DRIVER_SORT = """
    def global_sort(ds, key):
        return sorted(ds.iter_rows(), key=key)
"""

RL019_BAD_COMPREHENSION = """
    def all_blocks(parent):
        blocks = [b for b in parent._iter_block_values()]
        return blocks
"""

RL019_BAD_BULK_GET = """
    import ray_tpu

    def resolve(refs):
        return ray_tpu.get([r for r in refs])
"""

RL019_GOOD_STREAMING_LOOP = """
    def count(ds):
        total = 0
        for block in ds._iter_block_values():
            total += len(block)
        return total
"""

RL019_GOOD_REF_ITERATION = """
    def ship(ds, fn):
        # refs are bounded metadata — iterating (even collecting) them
        # never materializes block bytes on the driver.
        refs = list(ds._iter_block_refs())
        return [fn.remote(r) for r in refs]
"""


def test_rl019_flags_list_over_row_iterator(tmp_path):
    findings = lint_src(tmp_path, RL019_BAD_LIST_OVER_ROWS,
                        rules=["RL019"])
    assert rule_ids(findings) == ["RL019"]
    assert "driver memory" in findings[0].message


def test_rl019_flags_driver_side_sorted(tmp_path):
    findings = lint_src(tmp_path, RL019_BAD_SORTED_DRIVER_SORT,
                        rules=["RL019"])
    assert rule_ids(findings) == ["RL019"]


def test_rl019_flags_block_comprehension(tmp_path):
    findings = lint_src(tmp_path, RL019_BAD_COMPREHENSION,
                        rules=["RL019"])
    assert rule_ids(findings) == ["RL019"]
    assert "_iter_block_values" in findings[0].message


def test_rl019_flags_bulk_get_of_ref_list(tmp_path):
    findings = lint_src(tmp_path, RL019_BAD_BULK_GET, rules=["RL019"])
    assert rule_ids(findings) == ["RL019"]
    assert "bulk get" in findings[0].message


def test_rl019_quiet_on_streaming_loop(tmp_path):
    assert lint_src(tmp_path, RL019_GOOD_STREAMING_LOOP,
                    rules=["RL019"]) == []


def test_rl019_quiet_on_ref_iteration(tmp_path):
    assert lint_src(tmp_path, RL019_GOOD_REF_ITERATION,
                    rules=["RL019"]) == []


def test_rl019_suppression_with_reason(tmp_path):
    src = RL019_BAD_LIST_OVER_ROWS.replace(
        "return list(ds.iter_rows())",
        "return list(ds.iter_rows())  "
        "# raylint: disable=RL019 — deliberate local-copy endpoint")
    assert lint_src(tmp_path, src, rules=["RL019"]) == []


def test_rl019_scoped_to_data_package(tmp_path):
    # Driver-side materialization in a control-plane package is not the
    # query tier's contract; RL019 only patrols the data plane (and
    # fixtures).
    pkg = tmp_path / "ray_tpu"
    serve = pkg / "serve"
    serve.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (serve / "__init__.py").write_text("")
    mod = serve / "router.py"
    mod.write_text(textwrap.dedent(RL019_BAD_LIST_OVER_ROWS))
    assert lint_file(str(mod), rule_ids=["RL019"]) == []
