"""raylint whole-program tests: RL014-RL017, the incremental cache, the
SARIF/exit-code contract, the unused-suppression audit, and the mutation
negative-controls.

The fixture pairs follow test_raylint.py's discipline (flag the bad
snippet, stay quiet on the prescribed fix).  The mutation controls are
the important novelty: they lint a COPY of the live package with one
real registration / knob declaration / confinement annotation deleted
and assert the corresponding rule fires — proving the project graph
resolves the actual codebase, not just these fixtures.
"""

import json
import os
import shutil
import subprocess
import sys
import textwrap
import time

import pytest

from ray_tpu.analysis.engine import lint_file, lint_paths_full

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "ray_tpu")


def write_tree(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def lint_tree(tmp_path, files, rules=None):
    root = write_tree(tmp_path, files)
    return lint_paths_full([str(root)], rules).findings


def rule_ids(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------------------ RL014

RL014_SERVER = """
    class Gcs:
        def __init__(self, server):
            server.register("get_thing", self._handle_get)
            server.register_raw("blob_get", self._handle_blob)
            server.register_instance(self, prefix="client_")

        def _handle_get(self, conn, data):
            return {"ok": True}

        def _handle_blob(self, conn, payload):
            return payload

        def handle_hello(self, conn, data=None):
            return {}
"""


def test_rl014_flags_unregistered_call(tmp_path):
    findings = lint_tree(tmp_path, {
        "pkg/server.py": RL014_SERVER,
        "pkg/client.py": 'def f(c):\n    return c.call("get_thingg", {})\n',
    }, rules=["RL014"])
    unregistered = [f for f in findings if "no server registers" in f.message]
    assert len(unregistered) == 1
    assert "get_thingg" in unregistered[0].message
    assert unregistered[0].path.endswith("client.py")


def test_rl014_quiet_on_registered_call_and_prefix_expansion(tmp_path):
    findings = lint_tree(tmp_path, {
        "pkg/server.py": RL014_SERVER,
        "pkg/client.py": """
            def f(c):
                c.call("get_thing", {})
                c.call_raw("blob_get", b"x")
                return c.call("client_hello")
        """,
    }, rules=["RL014"])
    assert findings == []


def test_rl014_flags_lane_mismatch_both_directions(tmp_path):
    findings = lint_tree(tmp_path, {
        "pkg/server.py": RL014_SERVER,
        "pkg/client.py": """
            def f(c):
                c.call_raw("get_thing", b"x")   # pickled handler, raw call
                return c.call("blob_get", {})   # raw handler, pickled call
        """,
    }, rules=["RL014"])
    mismatches = [f for f in findings if "lane mismatch" in f.message]
    assert len(mismatches) == 2


def test_rl014_flags_handler_arity(tmp_path):
    findings = lint_tree(tmp_path, {
        "pkg/server.py": """
            class Srv:
                def __init__(self, server):
                    server.register("narrow", self._narrow)

                def _narrow(self, conn):
                    return {}
        """,
        "pkg/client.py": 'def f(c):\n    return c.call("narrow", {})\n',
    }, rules=["RL014"])
    assert any("handler(conn, data)" in f.message for f in findings)


def test_rl014_quiet_on_conn_data_signatures(tmp_path):
    findings = lint_tree(tmp_path, {
        "pkg/server.py": """
            class Srv:
                def __init__(self, server):
                    server.register("a", self._a)
                    server.register("b", lambda conn, data: {})

                def _a(self, conn, data=None):
                    return {}
        """,
        "pkg/client.py": """
            def f(c):
                c.call("a")
                return c.call("b")
        """,
    }, rules=["RL014"])
    assert findings == []


def test_rl014_flags_dead_endpoint(tmp_path):
    findings = lint_tree(tmp_path, {
        "pkg/server.py": """
            def serve(server, handler):
                server.register("orphan", handler)
        """,
    }, rules=["RL014"])
    assert rule_ids(findings) == ["RL014"]
    assert "dead endpoint" in findings[0].message


def test_rl014_dead_quiet_on_literal_reference_elsewhere(tmp_path):
    # A dispatch-table mention counts: wrappers like
    # `self._call("collective_take", ...)` reach endpoints the
    # call-site index can't see.
    findings = lint_tree(tmp_path, {
        "pkg/server.py": """
            def serve(server, handler):
                server.register("orphan", handler)
        """,
        "pkg/client.py": 'METHODS = ["orphan"]\n',
    }, rules=["RL014"])
    assert findings == []


def test_rl014_dead_quiet_on_direct_handler_call(tmp_path):
    # In-process injectors call handle_* methods directly (the chaos
    # plane idiom) — that is a live reference.
    findings = lint_tree(tmp_path, {
        "pkg/server.py": """
            class Srv:
                def __init__(self, server):
                    server.register_instance(self)

                def handle_kill(self, conn, data):
                    return {}
        """,
        "pkg/injector.py": """
            def inject(srv):
                return srv.handle_kill(None, {})
        """,
    }, rules=["RL014"])
    assert findings == []


def test_rl014_register_instance_covers_inherited_and_nonself(tmp_path):
    # The runtime expands dir(obj): inherited handle_* methods and
    # register_instance on a non-self object both register — the index
    # must agree (same-file resolution).
    findings = lint_tree(tmp_path, {
        "pkg/server.py": """
            class Base:
                def handle_ping2(self, conn, data=None):
                    return {}

            class Gateway:
                def handle_gw_put(self, conn, data):
                    return {}

            class Srv(Base):
                def __init__(self, server):
                    server.register_instance(self)
                    gw = Gateway()
                    server.register_instance(gw, prefix="x_")
        """,
        "pkg/client.py": """
            def f(c):
                c.call("ping2")
                return c.call("x_gw_put", {})
        """,
    }, rules=["RL014"])
    assert findings == [], [f.render() for f in findings]


def test_rl014_suppression_with_reason(tmp_path):
    findings = lint_tree(tmp_path, {
        "pkg/server.py": """
            def serve(server, handler):
                server.register("orphan", handler)  # raylint: disable=RL014 — external caller
        """,
    }, rules=["RL014"])
    assert findings == []


# ------------------------------------------------------------------ RL015

RL015_CONFIG = """
    _TABLE = {}

    def _flag(name, type_, default, doc=""):
        _TABLE[name] = (type_, default, doc)

    _flag("alpha", int, 1, "used and documented")
    _flag("beta", int, 2, "declared but never read")
"""


def test_rl015_flags_undeclared_read_and_write(tmp_path):
    findings = lint_tree(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/config.py": RL015_CONFIG,
        "pkg/user.py": """
            from pkg.config import GLOBAL_CONFIG

            def f():
                GLOBAL_CONFIG.gama = 3
                return GLOBAL_CONFIG.alpha + GLOBAL_CONFIG.delta
        """,
        "docs/CONFIG.md": "alpha beta\n",
    }, rules=["RL015"])
    msgs = [f.message for f in findings]
    assert any("read of undeclared config knob 'delta'" in m for m in msgs)
    assert any("write to undeclared config knob 'gama'" in m for m in msgs)
    # beta: declared, never read
    assert any("'beta' is declared but never read" in m for m in msgs)
    assert len(findings) == 3


def test_rl015_quiet_on_declared_read_and_methods(tmp_path):
    findings = lint_tree(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/config.py": RL015_CONFIG,
        "pkg/user.py": """
            from pkg.config import GLOBAL_CONFIG

            def f():
                GLOBAL_CONFIG.refresh()
                GLOBAL_CONFIG.alpha = 5
                return GLOBAL_CONFIG.alpha + GLOBAL_CONFIG.beta
        """,
        "docs/CONFIG.md": "alpha beta\n",
    }, rules=["RL015"])
    assert findings == []


def test_rl015_flags_knob_missing_from_docs(tmp_path):
    findings = lint_tree(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/config.py": RL015_CONFIG,
        "pkg/user.py": """
            from pkg.config import GLOBAL_CONFIG

            def f():
                return GLOBAL_CONFIG.alpha + GLOBAL_CONFIG.beta
        """,
        "docs/CONFIG.md": "alpha only\n",
    }, rules=["RL015"])
    assert rule_ids(findings) == ["RL015"]
    assert "'beta' is missing from the docs" in findings[0].message


def test_rl015_docs_check_skipped_without_docs_dir(tmp_path):
    findings = lint_tree(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/config.py": RL015_CONFIG,
        "pkg/user.py": """
            from pkg.config import GLOBAL_CONFIG

            def f():
                return GLOBAL_CONFIG.alpha + GLOBAL_CONFIG.beta
        """,
    }, rules=["RL015"])
    assert findings == []


# ------------------------------------------------------------------ RL016

RL016_BAD_ESCAPE = """
    class Lane:
        def __init__(self):
            self._chans = {}  # raylint: confine=loop

        def _touch(self):
            self._chans["x"] = 1

        def go(self, loop):
            loop.run_in_executor(None, self._touch)
"""

RL016_GOOD_ESCAPE = """
    class Lane:
        def __init__(self):
            self._chans = {}  # raylint: confine=loop

        def _resolve(self):
            return open("/dev/null")

        def go(self, loop):
            self._chans["x"] = 1
            return loop.run_in_executor(None, self._resolve)
"""


def test_rl016_flags_confined_attr_in_executor_target(tmp_path):
    findings = lint_tree(tmp_path, {"pkg/lane.py": RL016_BAD_ESCAPE},
                         rules=["RL016"])
    assert rule_ids(findings) == ["RL016"]
    assert "_chans" in findings[0].message
    assert "escape" in findings[0].message


def test_rl016_quiet_on_escape_not_touching_confined_state(tmp_path):
    assert lint_tree(tmp_path, {"pkg/lane.py": RL016_GOOD_ESCAPE},
                     rules=["RL016"]) == []


def test_rl016_flags_one_hop_reach(tmp_path):
    findings = lint_tree(tmp_path, {"pkg/lane.py": """
        import threading

        class Lane:
            def __init__(self):
                self._chans = {}  # raylint: confine=loop

            def _touch(self):
                self._chans.pop("x", None)

            def _work(self):
                self._touch()

            def go(self):
                threading.Thread(target=self._work, daemon=True).start()
    """}, rules=["RL016"])
    assert rule_ids(findings) == ["RL016"]


def test_rl016_flags_closure_escape(tmp_path):
    findings = lint_tree(tmp_path, {"pkg/lane.py": """
        class Lane:
            def __init__(self):
                self._chans = {}  # raylint: confine=loop

            def go(self, loop):
                def work():
                    self._chans["x"] = 1
                loop.run_in_executor(None, work)
    """}, rules=["RL016"])
    assert rule_ids(findings) == ["RL016"]


def test_rl016_flags_unannotated_sibling(tmp_path):
    findings = lint_tree(tmp_path, {"pkg/lane.py": """
        class Lane:
            def __init__(self):
                self._chans = {}  # raylint: confine=loop
                self._depths = {}

            def on_req(self, rid):
                self._depths[rid] = 1
    """}, rules=["RL016"])
    assert rule_ids(findings) == ["RL016"]
    assert "_depths" in findings[0].message
    assert "annotate" in findings[0].message


def test_rl016_sibling_quiet_when_annotated_or_locked(tmp_path):
    assert lint_tree(tmp_path, {"pkg/a.py": """
        class Lane:
            def __init__(self):
                self._chans = {}  # raylint: confine=loop
                # raylint: confine=loop
                self._depths = {}

            def on_req(self, rid):
                self._depths[rid] = 1
    """}, rules=["RL016"]) == []
    # A class with a lock has a mixed discipline: unannotated state is
    # presumed lock-protected, not loop-confined.
    assert lint_tree(tmp_path, {"pkg/b.py": """
        import threading

        class Lane:
            def __init__(self):
                self._lock = threading.Lock()
                self._chans = {}  # raylint: confine=loop
                self._depths = {}

            def on_req(self, rid):
                with self._lock:
                    self._depths[rid] = 1
    """}, rules=["RL016"]) == []


def test_rl016_quiet_without_annotations(tmp_path):
    # No confine markers, no contract: RL016 has nothing to enforce.
    assert lint_tree(tmp_path, {"pkg/lane.py": """
        class Lane:
            def __init__(self):
                self._chans = {}

            def _touch(self):
                self._chans["x"] = 1

            def go(self, loop):
                loop.run_in_executor(None, self._touch)
    """}, rules=["RL016"]) == []


# ------------------------------------------------------------------ RL017

RL017_BAD_DELEGATE = """
    from ray_tpu.core.rpc import DEFERRED

    class Srv:
        def handle_fetch(self, conn, data):
            self._begin(conn, conn.current_msg_id)
            return DEFERRED

        def _begin(self, conn, mid):
            self.log(mid)   # bookkeeping only: nobody can ever reply
"""

RL017_GOOD_DELEGATE_PARKS = """
    from ray_tpu.core.rpc import DEFERRED

    class Srv:
        def handle_fetch(self, conn, data):
            self._begin(conn, conn.current_msg_id)
            return DEFERRED

        def _begin(self, conn, mid):
            self._waiters.append((conn, mid))
"""

RL017_BAD_UNGUARDED_CLOSURE = """
    from ray_tpu.core.rpc import DEFERRED

    class Srv:
        def handle_fetch(self, conn, data):
            self._begin(conn, conn.current_msg_id, data)
            return DEFERRED

        def _begin(self, conn, mid, data):
            def done(result):
                payload = transform(result)
                conn.reply(mid, "fetch", payload)
            self.executor.submit(done)
"""

RL017_GOOD_GUARDED_CLOSURE = """
    from ray_tpu.core.rpc import DEFERRED

    class Srv:
        def handle_fetch(self, conn, data):
            self._begin(conn, conn.current_msg_id, data)
            return DEFERRED

        def _begin(self, conn, mid, data):
            def done(result):
                try:
                    conn.reply(mid, "fetch", transform(result))
                except Exception as e:
                    conn.reply(mid, "fetch", None, error=str(e))
            self.executor.submit(done)
"""


def test_rl017_flags_delegate_that_never_replies(tmp_path):
    path = tmp_path / "srv.py"
    path.write_text(textwrap.dedent(RL017_BAD_DELEGATE))
    findings = lint_file(str(path), rule_ids=["RL017"])
    assert rule_ids(findings) == ["RL017"]
    assert "_begin" in findings[0].message


def test_rl017_quiet_when_delegate_parks(tmp_path):
    path = tmp_path / "srv.py"
    path.write_text(textwrap.dedent(RL017_GOOD_DELEGATE_PARKS))
    assert lint_file(str(path), rule_ids=["RL017"]) == []


def test_rl017_flags_unguarded_closure_in_delegate(tmp_path):
    # RL001's blind spot: the closure lives in the helper, which does
    # not itself return DEFERRED.
    path = tmp_path / "srv.py"
    path.write_text(textwrap.dedent(RL017_BAD_UNGUARDED_CLOSURE))
    findings = lint_file(str(path), rule_ids=["RL017"])
    assert rule_ids(findings) == ["RL017"]
    assert "can raise before replying" in findings[0].message


def test_rl017_quiet_on_guarded_closure_in_delegate(tmp_path):
    path = tmp_path / "srv.py"
    path.write_text(textwrap.dedent(RL017_GOOD_GUARDED_CLOSURE))
    assert lint_file(str(path), rule_ids=["RL017"]) == []


def test_rl017_flags_no_visible_completion_path(tmp_path):
    path = tmp_path / "srv.py"
    path.write_text(textwrap.dedent("""
        from ray_tpu.core.rpc import DEFERRED

        def handle_take(conn, data):
            validate(data)
            return DEFERRED
    """))
    findings = lint_file(str(path), rule_ids=["RL017"])
    assert rule_ids(findings) == ["RL017"]
    assert "nothing visible" in findings[0].message


def test_rl017_quiet_on_subscripted_park(tmp_path):
    # The gcs collective idiom: the park call's receiver is a subscript
    # (`slot["waiters"].append(...)`) and the msg id rides inline as
    # `conn.current_msg_id` — both must register as a park.
    path = tmp_path / "srv.py"
    path.write_text(textwrap.dedent("""
        from ray_tpu.core.rpc import DEFERRED

        def handle_take(conn, data, rec):
            slot = rec["mailbox"].setdefault(data["key"], {"waiters": []})
            slot["waiters"].append((conn, conn.current_msg_id))
            return DEFERRED
    """))
    assert lint_file(str(path), rule_ids=["RL017"]) == []


def test_rl017_handoff_counts_only_for_the_connection(tmp_path):
    # Passing the conn onward is a handoff (one-hop contract reached);
    # passing only the msg id is bookkeeping.
    path = tmp_path / "srv.py"
    path.write_text(textwrap.dedent("""
        from ray_tpu.core.rpc import DEFERRED

        class Srv:
            def handle_fetch(self, conn, data):
                self._begin(conn, conn.current_msg_id)
                return DEFERRED

            def _begin(self, conn, mid):
                self._transport.send_later(conn, mid)
    """))
    assert lint_file(str(path), rule_ids=["RL017"]) == []


# ------------------------------------------------------------------ RL023
# (whole-program: PartitionSpec literals vs the union of declared mesh
# axes, joined over the per-file jax_extract summaries)

RL023_MESH = """
    import numpy as np
    from jax.sharding import Mesh

    def build(devices):
        return Mesh(np.asarray(devices).reshape(2, 4), ("dp", "tp"))
"""


def test_rl023_flags_undeclared_axis(tmp_path):
    findings = lint_tree(tmp_path, {
        "pkg/mesh.py": RL023_MESH,
        "pkg/model.py": """
            from jax.sharding import PartitionSpec as P

            SPEC = P("dp", "model")
        """,
    }, rules=["RL023"])
    assert rule_ids(findings) == ["RL023"]
    assert "'model'" in findings[0].message
    assert findings[0].path.endswith("model.py")


def test_rl023_flags_trailing_none_spec(tmp_path):
    findings = lint_tree(tmp_path, {
        "pkg/mesh.py": RL023_MESH,
        "pkg/model.py": """
            from jax.sharding import PartitionSpec as P

            SPEC = P("dp", None)
        """,
    }, rules=["RL023"])
    assert rule_ids(findings) == ["RL023"]
    assert "trailing" in findings[0].message


def test_rl023_quiet_on_declared_axes(tmp_path):
    findings = lint_tree(tmp_path, {
        "pkg/mesh.py": RL023_MESH,
        "pkg/model.py": """
            from jax.sharding import PartitionSpec as P

            ROWS = P("dp", "tp")
            INNER = P(None, "tp")
            PAIR = P(("dp", "tp"))
        """,
    }, rules=["RL023"])
    assert findings == []


def test_rl023_axis_check_needs_a_declared_mesh(tmp_path):
    # With no mesh declaration anywhere in the tree there is nothing to
    # check axis names against; only the trailing-None check stays live.
    findings = lint_tree(tmp_path, {
        "pkg/model.py": """
            from jax.sharding import PartitionSpec as P

            SPEC = P("anything")
        """,
    }, rules=["RL023"])
    assert findings == []


def test_rl023_shardspec_kwargs_declare_multi_axes(tmp_path):
    # A multi-axis gang ShardSpec(tp=, pp=, sp=) is a mesh declaration:
    # specs over those axes are quiet, a name no spec anywhere declares
    # still fires.
    findings = lint_tree(tmp_path, {
        "pkg/gang.py": """
            from ray_tpu.shardgroup import ShardSpec

            SPEC = ShardSpec(tp=4, pp=2)
        """,
        "pkg/model.py": """
            from jax.sharding import PartitionSpec as P

            STAGE = P("pp", "tp")
            BAD = P("pp", "sp")
        """,
    }, rules=["RL023"])
    assert rule_ids(findings) == ["RL023"]
    assert "'sp'" in findings[0].message


def test_rl023_shardspec_size_one_axis_is_not_declared(tmp_path):
    # shardgroup's mesh_axes drops size-1 axes, so a literal pp=1 must
    # not license P("pp") — but a RUNTIME width (pp=n) may be > 1 and
    # counts as declared.
    base = {
        "pkg/model.py": """
            from jax.sharding import PartitionSpec as P

            STAGE = P("pp")
        """,
    }
    findings = lint_tree(tmp_path, {
        **base,
        "pkg/gang.py": """
            from ray_tpu.shardgroup import ShardSpec

            SPEC = ShardSpec(tp=2, pp=1)
        """,
    }, rules=["RL023"])
    assert rule_ids(findings) == ["RL023"]
    assert "'pp'" in findings[0].message

    findings = lint_tree(tmp_path, {
        **base,
        "pkg/gang.py": """
            from ray_tpu.shardgroup import ShardSpec

            def spec(n):
                return ShardSpec(tp=2, pp=n)
        """,
    }, rules=["RL023"])
    assert findings == []


def test_rl023_meshspec_axes_kwarg_declares(tmp_path):
    findings = lint_tree(tmp_path, {
        "pkg/mesh.py": """
            from ray_tpu.parallel.mesh import MeshSpec

            SPEC = MeshSpec(axes={"dp": 2, "tp": 4})
        """,
        "pkg/model.py": """
            from jax.sharding import PartitionSpec as P

            ROWS = P("dp", "tp")
        """,
    }, rules=["RL023"])
    assert findings == []


def test_rl023_finding_cites_the_owning_rule_pattern(tmp_path):
    # A hit inside a match_partition_rules table names the rule's regex,
    # so a bad axis in a 30-row table is attributable at a glance.
    findings = lint_tree(tmp_path, {
        "pkg/mesh.py": RL023_MESH,
        "pkg/rules.py": """
            from jax.sharding import PartitionSpec as P

            RULES = (
                (r"embed$", P("tp")),
                (r"wq/kernel$", P(None, "model")),
            )
        """,
    }, rules=["RL023"])
    assert rule_ids(findings) == ["RL023"]
    assert "wq/kernel$" in findings[0].message
    assert "'model'" in findings[0].message


# ------------------------------------------- mutation negative-controls


def copy_package(tmp_path) -> str:
    dst = str(tmp_path / "ray_tpu")
    shutil.copytree(PKG, dst, ignore=shutil.ignore_patterns(
        "__pycache__", ".raylint_cache", "_native", "*.so"))
    return dst


def mutate(root: str, rel: str, needle: str, replacement: str) -> None:
    path = os.path.join(root, rel)
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    assert needle in src, f"mutation target vanished from {rel}: {needle!r}"
    with open(path, "w", encoding="utf-8") as f:
        f.write(src.replace(needle, replacement, 1))


def test_mutation_removing_live_registration_fires_rl014(tmp_path):
    root = copy_package(tmp_path)
    # direct_call is the task fast path: the owner pushes specs at it
    # from core/direct_task.py, so dropping the registration must
    # surface as an unregistered call site.
    mutate(root, "core/worker.py",
           'self.direct_server.register("direct_call", '
           'self._handle_direct_call)',
           "pass")
    findings = [f for f in lint_paths_full([root], ["RL014"]).findings
                if '"direct_call"' in f.message]
    assert findings, "RL014 did not notice the removed registration"


def test_mutation_removing_live_knob_declaration_fires_rl015(tmp_path):
    root = copy_package(tmp_path)
    mutate(root, "core/config.py",
           '_flag("rpc_call_timeout_s", float, 120.0, '
           '"Default RPC call timeout")',
           "")
    findings = [f for f in lint_paths_full([root], ["RL015"]).findings
                if "rpc_call_timeout_s" in f.message]
    assert findings, "RL015 did not notice the removed knob declaration"
    assert any("undeclared" in f.message for f in findings)


def test_mutation_removing_confine_annotation_fires_rl016(tmp_path):
    root = copy_package(tmp_path)
    mutate(root, "tenancy/admission.py",
           "self._queues: Dict[tuple, Deque[_Waiter]] = {}  "
           "# raylint: confine=loop",
           "self._queues: Dict[tuple, Deque[_Waiter]] = {}")
    findings = [f for f in lint_paths_full([root], ["RL016"]).findings
                if "_queues" in f.message]
    assert findings, "RL016 did not notice the dropped annotation"


def test_mutation_traced_branch_in_jitted_epoch_fires_rl020(tmp_path):
    root = copy_package(tmp_path)
    # The KL tail-pick in the jitted scan epoch is dict-KEY membership
    # (static); branching on the traced KL VALUE instead is the classic
    # retrace hazard.
    mutate(root, "rllib/learner.py",
           'if "kl" in metrics:',
           'if metrics["kl"].mean() > 0:')
    findings = [f for f in lint_paths_full([root], ["RL020"]).findings
                if "traced" in f.message]
    assert findings, "RL020 did not notice the traced-value branch"


def test_mutation_dropping_sync_suppression_fires_rl021(tmp_path):
    root = copy_package(tmp_path)
    # The rollout loop's per-step device_get is the env-step contract
    # and carries a reasoned suppression; deleting the comment proves
    # RL021 resolves the live loop, not just fixtures.
    mutate(root, "rllib/rollout.py",
           "host = jax.device_get(out)  # raylint: disable=RL021 — "
           "per-step sync is the env-step contract",
           "host = jax.device_get(out)")
    findings = [f for f in lint_paths_full([root], ["RL021"]).findings
                if "sample" in f.message]
    assert findings, "RL021 did not notice the unsuppressed loop sync"


def test_mutation_removing_donate_rebind_guard_fires_rl022(tmp_path):
    root = copy_package(tmp_path)
    # The draft-prefill lockstep rebinds the donated draft arenas in
    # the same statement — the RL022 guard. Bind the result to a temp
    # and keep an alias read of the donated name instead.
    mutate(root, "inference/engine.py",
           "            self._draft_arenas = self._call(\n"
           '                "draft_prefill", self._draft_prefill_fn,\n'
           "                self._draft_params, self._draft_arenas, "
           "*args[:4])",
           "            fresh = self._call(\n"
           '                "draft_prefill", self._draft_prefill_fn,\n'
           "                self._draft_params, self._draft_arenas, "
           "*args[:4])\n"
           "            self._draft_sync = self._draft_arenas\n"
           "            self._draft_arenas = fresh")
    findings = [f for f in lint_paths_full([root], ["RL022"]).findings
                if "_draft_arenas" in f.message]
    assert findings, "RL022 did not notice the read of the donated arenas"


def test_mutation_adding_trailing_none_spec_fires_rl023(tmp_path):
    root = copy_package(tmp_path)
    # Reintroduce the PR-8 bug shape: a trailing literal None on the
    # ring-attention shard_map spec.
    mutate(root, "ops/ring_attention.py",
           "spec = P(data_axes, None, sp_axis)",
           "spec = P(data_axes, None, sp_axis, None)")
    findings = [f for f in lint_paths_full([root], ["RL023"]).findings
                if "trailing" in f.message
                and f.path.endswith("ring_attention.py")]
    assert findings, "RL023 did not notice the trailing-None spec"


def test_mutation_steady_state_write_to_captured_attr_fires_rl024(tmp_path):
    root = copy_package(tmp_path)
    # LlamaSampler's jitted decode_step closure captures self._max_seq;
    # rebinding it per batch makes the capture stale (jit burned the
    # first-trace value in).
    mutate(root, "serve/examples.py",
           "pad = min(pad, self._max_seq)",
           "pad = min(pad, self._max_seq)\n        self._max_seq = pad")
    findings = [f for f in lint_paths_full([root], ["RL024"]).findings
                if "_max_seq" in f.message]
    assert findings, "RL024 did not notice the stale jit capture"


def test_project_rules_see_whole_package_from_subset_paths():
    """Linting one file (or a subdirectory) must not produce
    partial-graph false positives: the graph is built over the owning
    package closure, findings reported only for the requested paths."""
    res = lint_paths_full([os.path.join(PKG, "core", "worker.py")],
                          ["RL014"])
    assert res.findings == [], [f.render() for f in res.findings]
    res = lint_paths_full([os.path.join(PKG, "core")], ["RL015"])
    assert res.findings == [], [f.render() for f in res.findings]


# --------------------------------------------------- incremental cache


def test_incremental_subset_run_does_not_evict_cache(tmp_path):
    """A --incremental run over a subset must leave the rest of the
    tree's cache entries intact (pruning is for deleted files only)."""
    cache_dir = str(tmp_path / "cache")
    root = write_tree(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/server.py": RL014_SERVER,
        "pkg/client.py": """
            def f(c):
                c.call("get_thing", {})
                c.call_raw("blob_get", b"x")
                return c.call("client_hello")
        """,
    })
    full = lint_paths_full([str(root)], incremental=True,
                           cache_dir=cache_dir)
    assert full.findings == [] and full.cache_misses == 3
    sub = lint_paths_full([str(root / "pkg" / "client.py")],
                          incremental=True, cache_dir=cache_dir)
    assert sub.findings == []
    again = lint_paths_full([str(root)], incremental=True,
                            cache_dir=cache_dir)
    assert again.cache_misses == 0, "subset run evicted unrelated entries"


def test_incremental_warm_run_is_identical_and_fast(tmp_path):
    cache_dir = str(tmp_path / "cache")
    paths = [os.path.join(PKG, "core"), os.path.join(PKG, "serve"),
             os.path.join(PKG, "tenancy")]
    t0 = time.perf_counter()
    cold = lint_paths_full(paths, incremental=True, cache_dir=cache_dir)
    cold_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    warm = lint_paths_full(paths, incremental=True, cache_dir=cache_dir)
    warm_s = time.perf_counter() - t0
    assert warm.cache_misses == 0 and warm.cache_hits == cold.cache_misses
    assert [f.as_dict() for f in warm.findings] == \
        [f.as_dict() for f in cold.findings]
    # The acceptance bound is <25% of the cold run; the sandbox ratio is
    # ~5%, so 50% here keeps the assertion meaningful without flaking
    # on a noisy 2-core box.
    assert warm_s < 0.5 * cold_s, (cold_s, warm_s)


def test_incremental_detects_edit_and_reanalyzes_one_file(tmp_path):
    cache_dir = str(tmp_path / "cache")
    client = ('def f(c):\n    c.call_raw("blob_get", b"x")\n'
              '    c.call("client_hello")\n'
              '    return c.call("{}", {{}})\n')
    root = write_tree(tmp_path, {
        "pkg/server.py": RL014_SERVER,
        "pkg/client.py": client.format("get_thing"),
    })
    cold = lint_paths_full([str(root)], incremental=True,
                           cache_dir=cache_dir)
    assert cold.findings == []
    (root / "pkg/client.py").write_text(client.format("get_thingg"))
    warm = lint_paths_full([str(root)], ["RL014"], incremental=True,
                           cache_dir=cache_dir)
    assert warm.cache_misses == 1 and warm.cache_hits == 1
    assert any("get_thingg" in f.message for f in warm.findings)


def test_incremental_jax_extract_only_change_updates_rl023(tmp_path):
    """An edit that only changes a file's `jax_extract` section (one
    PartitionSpec axis literal — no per-file rule cares) must flow
    through the cached summaries into the RL023 project join."""
    cache_dir = str(tmp_path / "cache")
    model = ('from jax.sharding import PartitionSpec as P\n\n'
             'SPEC = P("dp", "{}")\n')
    root = write_tree(tmp_path, {"pkg/mesh.py": RL023_MESH})
    (root / "pkg" / "model.py").write_text(model.format("tp"))
    cold = lint_paths_full([str(root)], incremental=True,
                           cache_dir=cache_dir)
    assert cold.findings == [] and cold.cache_misses == 2
    (root / "pkg" / "model.py").write_text(model.format("model"))
    warm = lint_paths_full([str(root)], incremental=True,
                           cache_dir=cache_dir)
    assert warm.cache_misses == 1 and warm.cache_hits == 1
    assert any(f.rule == "RL023" and "'model'" in f.message
               for f in warm.findings)


def test_incremental_cache_invalidates_on_rule_change(tmp_path, monkeypatch):
    from ray_tpu.analysis import engine

    cache_dir = str(tmp_path / "cache")
    root = write_tree(tmp_path, {"pkg/a.py": "x = 1\n"})
    cold = lint_paths_full([str(root)], incremental=True,
                           cache_dir=cache_dir)
    assert cold.cache_misses == 1
    monkeypatch.setattr(engine, "_tool_fingerprint", lambda: "changed")
    rerun = lint_paths_full([str(root)], incremental=True,
                            cache_dir=cache_dir)
    assert rerun.cache_misses == 1, "stale cache survived a rule change"


# -------------------------------------------------- CLI contract: SARIF,
# exit codes, unused suppressions, timings


def run_cli(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu.analysis", *args],
        capture_output=True, text=True, cwd=cwd)


def test_cli_sarif_output_and_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import threading

        def spawn():
            threading.Thread(target=print).start()
    """))
    proc = run_cli([str(bad), "--format", "sarif"])
    assert proc.returncode == 1  # findings -> 1
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "raylint"
    results = run["results"]
    assert results and results[0]["ruleId"] == "RL005"
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["region"]["startLine"] == 5
    assert loc["artifactLocation"]["uri"].endswith("bad.py")
    rules_meta = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"RL001", "RL014", "RL017",
            "RL020", "RL021", "RL022", "RL023", "RL024"} <= rules_meta

    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    assert run_cli([str(good), "--format", "sarif"]).returncode == 0  # clean
    assert run_cli([str(good), "--rules", "RL999"]).returncode == 2  # usage


def test_cli_retired_rl006_errors_with_pointer(tmp_path):
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    proc = run_cli([str(good), "--rules", "RL006"])
    assert proc.returncode == 2
    assert "retired" in proc.stderr and "RL020" in proc.stderr


def test_cli_unknown_rule_hints_at_catalog(tmp_path):
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    proc = run_cli([str(good), "--rules", "RL999"])
    assert proc.returncode == 2
    assert "unknown rule" in proc.stderr
    assert "--list-rules" in proc.stderr


def test_cli_list_rules_catalog():
    proc = run_cli(["--list-rules"])
    assert proc.returncode == 0
    for rid in ("RL001", "RL014", "RL020", "RL021",
                "RL022", "RL023", "RL024"):
        assert rid in proc.stdout, rid
    assert "scope:" in proc.stdout
    assert "[file]" in proc.stdout and "[project]" in proc.stdout
    # The retired alias stays documented in the catalog.
    assert "RL006" in proc.stdout
    assert "superseded by RL020" in proc.stdout


def test_cli_unused_suppression_report(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text(textwrap.dedent("""
        import threading

        def spawn():
            threading.Thread(target=print).start()  # raylint: disable=RL005

        def clean():
            return 1  # raylint: disable=RL002
    """))
    proc = run_cli([str(mod), "--report-unused-suppressions"])
    assert proc.returncode == 1
    assert "unused suppression of RL002" in proc.stderr
    assert "RL005" not in proc.stderr  # that one still fires -> used
    # The audit needs the full rule set.
    proc = run_cli([str(mod), "--report-unused-suppressions",
                    "--rules", "RL005"])
    assert proc.returncode == 2


def test_cli_rules_subset_still_reports_syntax_errors(tmp_path):
    # --rules must never let an unparseable file lint clean: RL000 is
    # always in scope.
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    proc = run_cli([str(bad), "--rules", "RL001"])
    assert proc.returncode == 1
    assert "RL000" in proc.stdout


def test_quoted_marker_is_documentation_not_a_directive(tmp_path):
    # A marker preceded by a backtick/quote (docstrings, rule-catalog
    # comments) neither suppresses nor counts for the audit.
    mod = tmp_path / "mod.py"
    mod.write_text(textwrap.dedent('''
        """Suppress with a trailing ``# raylint: disable=RL005``."""
        import threading

        def spawn():
            # the idiom is `# raylint: disable=RL005` with a reason
            threading.Thread(target=print).start()
    '''))
    proc = run_cli([str(mod), "--report-unused-suppressions"])
    assert proc.returncode == 1
    assert "RL005" in proc.stdout          # finding NOT suppressed
    assert "unused suppression" not in proc.stderr  # mentions not audited


def test_cli_timings_table(tmp_path):
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    proc = run_cli([str(good), "--timings"])
    assert proc.returncode == 0
    assert "raylint timings" in proc.stderr
    assert "RL014" in proc.stderr


def test_package_has_no_unused_suppressions():
    """Satellite contract: every `# raylint: disable=` comment in the
    package still earns its keep."""
    proc = run_cli(["ray_tpu/", "--report-unused-suppressions"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
