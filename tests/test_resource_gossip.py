"""Streaming resource gossip (SURVEY §2.1 N12, reference Ray Syncer).

Raylets push availability deltas the moment their ledger changes
(coalesced to resource_delta_min_interval_ms); the GCS re-publishes them
as per-node DELTA messages on the RESOURCES channel. Peers' cluster
views must therefore refresh in ~the delta interval even when the
heartbeat period (the anti-entropy full report) is far longer."""

import time

import pytest

import ray_tpu
from ray_tpu.core.config import GLOBAL_CONFIG


@pytest.fixture()
def slow_heartbeat_cluster():
    """Two raylets with a heartbeat so slow that any view freshness must
    come from streamed deltas."""
    from ray_tpu.cluster_utils import Cluster

    old_hb = GLOBAL_CONFIG.raylet_heartbeat_period_ms
    old_thresh = GLOBAL_CONFIG.health_check_failure_threshold
    GLOBAL_CONFIG.raylet_heartbeat_period_ms = 30_000
    # Health checks ride their own channel but the death verdict must not
    # outpace the stretched heartbeat on a slow CI box.
    GLOBAL_CONFIG.health_check_failure_threshold = 60
    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=1)
    cluster.add_node(num_cpus=1, resources={"pool": 2})
    cluster.wait_for_nodes()
    cluster.connect()
    try:
        yield cluster
    finally:
        GLOBAL_CONFIG.raylet_heartbeat_period_ms = old_hb
        GLOBAL_CONFIG.health_check_failure_threshold = old_thresh
        cluster.shutdown()


def _pool_entry(raylet):
    for entry in raylet._cluster_view.values():
        if entry.get("total", {}).get("pool"):
            return entry
    return None


def test_deltas_propagate_faster_than_heartbeat(slow_heartbeat_cluster):
    cluster = slow_heartbeat_cluster
    observer = cluster.raylets[0]  # the first raylet's view
    # Initial view arrives via registration broadcast.
    deadline = time.monotonic() + 10
    while _pool_entry(observer) is None and time.monotonic() < deadline:
        time.sleep(0.1)
    entry = _pool_entry(observer)
    assert entry is not None, "pool node never appeared in peer view"
    assert entry["available"].get("pool") == 2.0

    @ray_tpu.remote(resources={"pool": 2}, num_cpus=0)
    def hold(sec):
        time.sleep(sec)
        return "done"

    ref = hold.remote(4.0)
    # Occupancy must show up in the PEER raylet's view well inside the
    # 30s heartbeat period — only a streamed delta can deliver it.
    deadline = time.monotonic() + 5
    seen_busy = False
    while time.monotonic() < deadline:
        entry = _pool_entry(observer)
        if entry and entry["available"].get("pool", 2.0) < 2.0:
            seen_busy = True
            break
        time.sleep(0.05)
    assert seen_busy, "resource occupancy never gossiped to the peer"

    assert ray_tpu.get(ref, timeout=30) == "done"
    # And the release gossips back just as fast (lease return + delta).
    lease_slack = GLOBAL_CONFIG.direct_lease_idle_s + 3
    deadline = time.monotonic() + lease_slack
    recovered = False
    while time.monotonic() < deadline:
        entry = _pool_entry(observer)
        if entry and entry["available"].get("pool") == 2.0:
            recovered = True
            break
        time.sleep(0.05)
    assert recovered, "resource release never gossiped to the peer"


def test_stale_heartbeat_cannot_clobber_fresher_delta(slow_heartbeat_cluster):
    """An in-flight heartbeat snapshot (taken before a delta) must not
    revert the delta it races — the version decides (gcs.py
    handle_heartbeat)."""
    cluster = slow_heartbeat_cluster
    rt = ray_tpu._global_runtime
    pool_raylet = [r for r in cluster.raylets
                   if r.resources.total.get("pool")][0]
    node_hex = pool_raylet.node_id.hex()
    gcs = rt.gcs
    cur = pool_raylet._resource_version

    gcs.call("resource_delta", {
        "node_id": pool_raylet.node_id,
        "resources_available": {"CPU": 1.0, "pool": 0.5},
        "resources_total": dict(pool_raylet.resources.total),
        "version": cur + 10})
    # The racing heartbeat carries an OLDER version and a stale snapshot.
    resp = gcs.call("heartbeat", {
        "node_id": pool_raylet.node_id,
        "resources_available": {"CPU": 1.0, "pool": 2.0},
        "resources_total": dict(pool_raylet.resources.total),
        "resource_version": cur + 9,
        "pending_demand": []})
    assert resp["registered"]
    view = gcs.call("get_resource_view", None)
    assert view[node_hex]["available"]["pool"] == 0.5, \
        "stale heartbeat reverted a fresher delta"
    # A heartbeat at/above the delta version applies normally.
    gcs.call("heartbeat", {
        "node_id": pool_raylet.node_id,
        "resources_available": {"CPU": 1.0, "pool": 2.0},
        "resources_total": dict(pool_raylet.resources.total),
        "resource_version": cur + 10,
        "pending_demand": []})
    view = gcs.call("get_resource_view", None)
    assert view[node_hex]["available"]["pool"] == 2.0


def test_stale_delta_versions_dropped(slow_heartbeat_cluster):
    """Out-of-order deltas must not regress a node's entry."""
    cluster = slow_heartbeat_cluster
    rt = ray_tpu._global_runtime
    pool_raylet = [r for r in cluster.raylets
                   if r.resources.total.get("pool")][0]
    node_hex = pool_raylet.node_id.hex()
    gcs = rt.gcs
    cur = pool_raylet._resource_version

    # A fresh delta lands...
    gcs.call("resource_delta", {
        "node_id": pool_raylet.node_id,
        "resources_available": {"CPU": 1.0, "pool": 1.5},
        "resources_total": dict(pool_raylet.resources.total),
        "version": cur + 100})
    # ...then a stale one (older version) must be ignored.
    resp = gcs.call("resource_delta", {
        "node_id": pool_raylet.node_id,
        "resources_available": {"CPU": 1.0, "pool": 0.0},
        "resources_total": dict(pool_raylet.resources.total),
        "version": cur + 99})
    assert resp.get("stale") is True
    view = gcs.call("get_resource_view", None)
    assert view[node_hex]["available"]["pool"] == 1.5
