"""RLlib slice: env dynamics, GAE, learner updates, PPO end-to-end."""

import numpy as np
import pytest


def test_cartpole_vector_env_dynamics():
    from ray_tpu.rllib.env import CartPoleVectorEnv

    env = CartPoleVectorEnv(n_envs=4, seed=0)
    obs = env.reset()
    assert obs.shape == (4, 4)
    total_done = 0
    rng = np.random.default_rng(0)
    for _ in range(300):
        obs, rewards, dones, infos = env.step(rng.integers(0, 2, size=4))
        assert obs.shape == (4, 4) and rewards.shape == (4,)
        total_done += int(dones.sum())
    # Random policy must fail episodes well before 300 steps.
    assert total_done > 0


def test_gae_simple_case():
    from ray_tpu.rllib.sample_batch import compute_gae

    # Single env, 3 steps, terminal at the end, gamma=1, lam=1:
    # advantages are reward-to-go minus value.
    rewards = np.array([[1.0], [1.0], [1.0]], dtype=np.float32)
    values = np.array([[0.5], [0.5], [0.5]], dtype=np.float32)
    dones = np.array([[False], [False], [True]])
    truncs = np.zeros_like(dones)
    # next_values[t] = V(s_{t+1}); the final step terminates (masked anyway).
    next_values = np.array([[0.5], [0.5], [0.0]], dtype=np.float32)
    adv, targets = compute_gae(rewards, values, dones, truncs, next_values,
                               gamma=1.0, lam=1.0)
    np.testing.assert_allclose(adv[:, 0], [2.5, 1.5, 0.5], atol=1e-6)
    np.testing.assert_allclose(targets[:, 0], [3.0, 2.0, 1.0], atol=1e-6)


def test_gae_truncation_bootstraps():
    from ray_tpu.rllib.sample_batch import compute_gae

    rewards = np.array([[1.0]], dtype=np.float32)
    values = np.array([[0.0]], dtype=np.float32)
    dones = np.array([[True]])
    next_values = np.array([[10.0]], dtype=np.float32)
    # Terminated: no bootstrap.
    adv_term, _ = compute_gae(rewards, values, dones,
                              np.array([[False]]), next_values,
                              gamma=0.5, lam=1.0)
    assert adv_term[0, 0] == pytest.approx(1.0)
    # Truncated: bootstraps gamma * V(next).
    adv_trunc, _ = compute_gae(rewards, values, dones,
                               np.array([[True]]), next_values,
                               gamma=0.5, lam=1.0)
    assert adv_trunc[0, 0] == pytest.approx(1.0 + 0.5 * 10.0)


def test_module_forward_shapes():
    import jax

    from ray_tpu.rllib.rl_module import DiscretePolicyModule, SpecDict

    mod = DiscretePolicyModule(SpecDict(obs_dim=4, n_actions=2))
    params = mod.init_params(jax.random.PRNGKey(0))
    obs = np.zeros((7, 4), np.float32)
    out = mod.forward_exploration(params, obs, jax.random.PRNGKey(1))
    assert out["actions"].shape == (7,) and out["vf"].shape == (7,)
    inf = mod.forward_inference(params, obs)
    assert set(np.asarray(inf["actions"]).tolist()) <= {0, 1}
    train = mod.forward_train(params, {"obs": obs,
                                       "actions": np.zeros(7, np.int64)})
    assert train["logp"].shape == (7,) and train["entropy"].shape == (7,)


def test_learner_update_reduces_loss():
    from ray_tpu.rllib.ppo import PPOConfig, PPOLearner
    from ray_tpu.rllib.rl_module import DiscretePolicyModule, SpecDict

    mod = DiscretePolicyModule(SpecDict(obs_dim=4, n_actions=2))
    learner = PPOLearner(mod, PPOConfig(lr=1e-2), seed=0)
    rng = np.random.default_rng(0)
    batch = {
        "obs": rng.normal(size=(64, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, 64),
        "logp": np.full(64, -0.69, np.float32),
        "vf_preds": np.zeros(64, np.float32),
        "advantages": rng.normal(size=64).astype(np.float32),
        "value_targets": rng.normal(size=64).astype(np.float32),
    }
    m1 = learner.update(batch)
    for _ in range(10):
        m2 = learner.update(batch)
    assert m2["vf_loss"] < m1["vf_loss"]
    assert np.isfinite(m2["total_loss"])


def test_rollout_worker_sample_layout():
    from ray_tpu.rllib.rollout import RolloutWorker

    w = RolloutWorker("CartPole-v1", n_envs=4, seed=0)
    batch = w.sample(16)
    assert batch["obs"].shape == (64, 4)
    assert batch["actions"].shape == (64,)
    assert batch["_next_vf"].shape == (64,)
    # Stats accumulate across sample calls.
    for _ in range(20):
        w.sample(16)
    stats = w.episode_stats()
    assert stats["episodes"] > 0
    assert stats["episode_reward_mean"] > 5


@pytest.mark.slow  # >10s wall; tier-1 truncation headroom (gate.sh runs full suite)
def test_ppo_solves_cartpole(ray_start_shared):
    """North-star learning test (reference rllib_learning_tests_*):
    PPO through actor rollout workers reaches reward >= 150."""
    from ray_tpu.rllib import PPO, PPOConfig

    algo = PPO(PPOConfig(
        env="CartPole-v1",
        num_rollout_workers=2,
        num_envs_per_worker=8,
        rollout_fragment_length=128,
        sgd_minibatch_size=256,
        num_sgd_iter=10,
        lr=1e-3,
        entropy_coeff=0.0,
        seed=0,
    ))
    best = 0.0
    try:
        for i in range(100):
            result = algo.train()
            r = result.get("episode_reward_mean")
            if r is not None:
                best = max(best, r)
            if best >= 150:
                break
        assert best >= 150, f"PPO failed to learn: best reward {best}"
    finally:
        algo.stop()


def test_ppo_save_restore(ray_start_shared, tmp_path):
    from ray_tpu.rllib import PPO, PPOConfig

    algo = PPO(PPOConfig(num_rollout_workers=1, num_envs_per_worker=4,
                         rollout_fragment_length=32, num_sgd_iter=2,
                         sgd_minibatch_size=64))
    try:
        algo.train()
        path = algo.save(str(tmp_path / "ckpt"))
        it = algo.iteration
        w1 = algo.get_weights()
    finally:
        algo.stop()

    algo2 = PPO(PPOConfig(num_rollout_workers=1, num_envs_per_worker=4,
                          rollout_fragment_length=32, num_sgd_iter=2,
                          sgd_minibatch_size=64))
    try:
        algo2.restore(path)
        assert algo2.iteration == it
        w2 = algo2.get_weights()
        import jax

        for a, b in zip(jax.tree.leaves(w1), jax.tree.leaves(w2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        algo2.train()  # restored algo keeps training
    finally:
        algo2.stop()


def test_vtrace_matches_numpy_reference():
    """On- and off-policy V-trace vs a direct numpy recursion."""
    import jax.numpy as jnp

    from ray_tpu.rllib.impala import vtrace_returns

    rng = np.random.default_rng(0)
    T, B = 12, 3
    gamma = 0.9
    behavior = rng.normal(-1.0, 0.3, (T, B)).astype(np.float32)
    target = behavior + rng.normal(0, 0.2, (T, B)).astype(np.float32)
    rewards = rng.normal(0, 1, (T, B)).astype(np.float32)
    values = rng.normal(0, 1, (T, B)).astype(np.float32)
    next_values = rng.normal(0, 1, (T, B)).astype(np.float32)
    terminateds = (rng.random((T, B)) < 0.1).astype(np.float32)
    truncateds = (rng.random((T, B)) < 0.05).astype(np.float32)
    truncateds = np.minimum(truncateds, 1 - terminateds)
    dones = np.maximum(terminateds, truncateds)

    vs, pg = vtrace_returns(
        jnp.asarray(behavior), jnp.asarray(target), jnp.asarray(rewards),
        jnp.asarray(terminateds), jnp.asarray(dones), jnp.asarray(values),
        jnp.asarray(next_values), gamma)
    vs, pg = np.asarray(vs), np.asarray(pg)

    # Direct recursion (vtrace paper eq. 1, trace cut at episode ends).
    rho = np.minimum(np.exp(target - behavior), 1.0)
    c = np.minimum(np.exp(target - behavior), 1.0)
    boot = gamma * (1 - terminateds)
    deltas = rho * (rewards + boot * next_values - values)
    acc = np.zeros(B, np.float32)
    vs_ref = np.zeros_like(values)
    for t in reversed(range(T)):
        acc = deltas[t] + gamma * (1 - dones[t]) * c[t] * acc
        vs_ref[t] = values[t] + acc
    np.testing.assert_allclose(vs, vs_ref, rtol=1e-5, atol=1e-5)

    vs_next = np.concatenate([vs_ref[1:], next_values[-1:]], axis=0)
    vs_next = np.where(dones > 0, next_values, vs_next)
    pg_ref = rho * (rewards + boot * vs_next - values)
    np.testing.assert_allclose(pg, pg_ref, rtol=1e-5, atol=1e-5)

    # On-policy, no episode ends: vs == TD(1) returns with bootstrap.
    zeros = np.zeros((T, 1), np.float32)
    r2 = rng.normal(0, 1, (T, 1)).astype(np.float32)
    v2 = rng.normal(0, 1, (T, 1)).astype(np.float32)
    nv2 = np.concatenate([v2[1:], rng.normal(0, 1, (1, 1)).astype(np.float32)])
    vs2, _ = vtrace_returns(
        jnp.asarray(zeros), jnp.asarray(zeros), jnp.asarray(r2),
        jnp.asarray(zeros), jnp.asarray(zeros), jnp.asarray(v2),
        jnp.asarray(nv2), gamma)
    ret = nv2[-1, 0]
    mc = np.zeros(T, np.float32)
    for t in reversed(range(T)):
        ret = r2[t, 0] + gamma * ret
        mc[t] = ret
    np.testing.assert_allclose(np.asarray(vs2)[:, 0], mc, rtol=1e-4,
                               atol=1e-4)


def test_impala_smoke_and_batch_shapes(ray_start_shared):
    from ray_tpu.rllib import IMPALA, IMPALAConfig

    algo = IMPALA(IMPALAConfig(
        num_rollout_workers=1, num_envs_per_worker=4,
        rollout_fragment_length=16, fragments_per_batch=2,
        replay_fragments=1, replay_buffer_num_slots=4,
        updates_per_iteration=2))
    try:
        m = algo.train()
        assert m["updates"] == 2
        assert np.isfinite(m["total_loss"])
        assert m["learner_sps"] > 0
        m2 = algo.train()
        assert m2["updates"] == 4
    finally:
        algo.stop()


@pytest.mark.slow  # >10s wall; tier-1 truncation headroom (gate.sh runs full suite)
def test_impala_learns_cartpole(ray_start_shared):
    """Second north-star workload (BASELINE.md: IMPALA async sampling +
    TPU learner): must reach reward >= 150 through async actor workers."""
    from ray_tpu.rllib import IMPALA, IMPALAConfig

    algo = IMPALA(IMPALAConfig(
        env="CartPole-v1",
        num_rollout_workers=2,
        num_envs_per_worker=16,
        rollout_fragment_length=64,
        fragments_per_batch=2,
        replay_fragments=2,
        replay_buffer_num_slots=8,
        updates_per_iteration=8,
        broadcast_interval=1,
        lr=2.5e-3,
        vf_loss_coeff=0.05,
        entropy_coeff=0.005,
        seed=0,
    ))
    best = 0.0
    try:
        # Async harvest ordering is nondeterministic, so the learning curve
        # varies run to run; the cap is sized for the slow tail.
        for i in range(90):
            result = algo.train()
            r = result.get("episode_reward_mean")
            if r is not None:
                best = max(best, r)
            if best >= 150:
                break
        assert best >= 150, f"IMPALA failed to learn: best reward {best}"
    finally:
        algo.stop()


def test_final_obs_at_done_rows():
    """Auto-reset must not swallow the true final observation: at a
    terminated row final_obs violates the CartPole limits while the
    returned (reset) obs is near zero."""
    from ray_tpu.rllib.env import CartPoleVectorEnv
    from ray_tpu.rllib.rollout import RolloutWorker

    env = CartPoleVectorEnv(n_envs=4, seed=0)
    env.reset()
    rng = np.random.default_rng(0)
    for _ in range(500):
        obs, rewards, dones, infos = env.step(
            rng.integers(0, 2, size=4))
        if dones.any():
            i = int(np.nonzero(dones)[0][0])
            final = infos["final_obs"][i]
            assert (abs(final[0]) > CartPoleVectorEnv.X_LIMIT
                    or abs(final[2]) > CartPoleVectorEnv.THETA_LIMIT)
            assert np.all(np.abs(obs[i]) <= 0.05)
            break
    else:
        pytest.fail("no episode terminated in 500 random steps")

    # The rollout worker patches next_vf at done rows with V(final_obs).
    w = RolloutWorker(CartPoleVectorEnv(n_envs=4, seed=1), n_envs=4, seed=1)
    batch = w.sample(64)
    T, n = batch["_shape"]
    dones = batch["dones"].reshape(T, n)
    assert dones.any(), "need at least one episode end in 64 steps"
    next_vf = batch["_next_vf"].reshape(T, n)
    vf = batch["vf_preds"].reshape(T, n)
    # At a done row, next_vf must differ from the naive shift (which would
    # be the reset obs value = vf of the next row).
    t = int(np.nonzero(dones[:-1].any(axis=1))[0][0])
    i = int(np.nonzero(dones[t])[0][0])
    assert not np.isclose(next_vf[t, i], vf[t + 1, i]), \
        "done-row next_vf still uses the reset obs value"


def test_impala_survives_worker_kill(ray_start_shared):
    """Reference FaultTolerantActorManager behavior: a dead rollout worker
    is replaced in place and training continues."""
    import ray_tpu
    from ray_tpu.rllib import IMPALA, IMPALAConfig

    algo = IMPALA(IMPALAConfig(
        num_rollout_workers=2, num_envs_per_worker=4,
        rollout_fragment_length=16, fragments_per_batch=2,
        updates_per_iteration=2))
    try:
        algo.train()
        ray_tpu.kill(algo.workers.workers[0])
        m = algo.train()  # must not hang or raise
        assert m["updates"] == 4
        assert np.isfinite(m["total_loss"])
    finally:
        algo.stop()


def test_appo_trains_cartpole(ray_start_shared):
    """APPO (reference rllib/algorithms/appo): IMPALA machinery + PPO
    clip + target policy; a couple of iterations must run and learn
    finite losses with target syncs."""
    import numpy as np

    from ray_tpu.rllib import APPOConfig

    algo = (APPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=1, num_envs_per_worker=4,
                      rollout_fragment_length=16)
            .training(updates_per_iteration=2, fragments_per_batch=2,
                      clip_param=0.3, use_kl_loss=True, kl_coeff=0.5)
            ).build()
    try:
        for _ in range(2):
            res = algo.train()
        assert np.isfinite(res["total_loss"])
        assert "kl" in res and "mean_ratio" in res
        # checkpoint round-trips target params
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            algo.save(d)
            algo.restore(d)
        res = algo.train()
        assert np.isfinite(res["total_loss"])
    finally:
        algo.stop()


def test_appo_learner_dp_parity():
    """APPO's target-anchored update matches single-device under dp=4."""
    import numpy as np

    from ray_tpu.rllib import sample_batch as sb
    from ray_tpu.rllib.appo import APPOConfig, APPOLearner
    from ray_tpu.rllib.rl_module import DiscretePolicyModule, SpecDict

    T, B, obs = 5, 8, 4
    rng = np.random.default_rng(3)
    batch = {
        sb.OBS: rng.standard_normal((T, B, obs)).astype(np.float32),
        "last_obs": rng.standard_normal((1, B, obs)).astype(np.float32),
        sb.ACTIONS: rng.integers(0, 2, (T, B)).astype(np.int32),
        sb.LOGP: np.log(np.full((T, B), 0.5, np.float32)),
        sb.REWARDS: rng.standard_normal((T, B)).astype(np.float32),
        sb.DONES: (rng.random((T, B)) < 0.1).astype(np.float32),
        "terminateds": np.zeros((T, B), np.float32),
        "behavior_next_vf": rng.standard_normal((T, B)).astype(np.float32),
    }
    cfg = APPOConfig(target_update_frequency=2)

    def make(n):
        module = DiscretePolicyModule(SpecDict(obs, 2), hidden=(16, 16))
        return APPOLearner(module, cfg, seed=0, num_devices=n)

    import jax

    l1, l4 = make(1), make(4)
    for _ in range(3):  # crosses a target sync boundary
        m1, m4 = l1.update(batch), l4.update(batch)
    assert abs(m1["total_loss"] - m4["total_loss"]) < 1e-4
    f1 = np.concatenate([np.asarray(x).ravel()
                         for x in jax.tree_util.tree_leaves(l1.get_weights())])
    f4 = np.concatenate([np.asarray(x).ravel()
                         for x in jax.tree_util.tree_leaves(l4.get_weights())])
    np.testing.assert_allclose(f1, f4, rtol=1e-4, atol=1e-5)
