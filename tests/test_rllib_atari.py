"""Atari north-star: real ALE when available, synthetic native-shape proof
otherwise.

Reference: `rllib/tuned_examples/ppo/atari-ppo.yaml:1-35` (the
reward-vs-timestep thresholds) and the release learning tests. `ale-py`
is not installable in this environment (zero egress), so the real-ALE
learning run is skip-gated; the identical pipeline — Atari connectors
(grayscale+resize+framestack), CNN module, uint8 rollout transport — is
proven on the synthetic Atari-shaped env at the NATIVE 210x160x3
observation shape.
"""

import numpy as np
import pytest

from ray_tpu.rllib.tuned_examples import (
    ATARI_PPO,
    TUNED_EXAMPLES,
    atari_available,
    run_tuned,
)


def test_tuned_example_registry_matches_reference():
    """The four Atari PPO north-stars exist with reference thresholds."""
    assert set(ATARI_PPO) == {"breakout-ppo", "beamrider-ppo", "qbert-ppo",
                              "spaceinvaders-ppo"}
    bk = TUNED_EXAMPLES["breakout-ppo"]
    cfg = bk.config_builder()
    assert cfg.env == "ALE/Breakout-v5"
    assert cfg.lr == 5e-5 and cfg.clip_param == 0.1
    assert bk.stop_reward == 30.0


@pytest.mark.skipif(not atari_available(),
                    reason="ale-py/gymnasium[atari] not installed")
def test_breakout_ppo_learns():
    """Real-ALE learning run (only where ale-py exists): PPO reaches the
    tuned-example threshold within a CI-scaled budget."""
    result = run_tuned(TUNED_EXAMPLES["breakout-ppo"],
                       max_timesteps=2_000_000)
    assert result.curve, "no reward curve recorded"
    assert result.best_reward >= 10.0, (
        f"Breakout PPO made no progress: {result.curve[-5:]}")


@pytest.mark.slow  # >10s wall; tier-1 truncation headroom (gate.sh runs full suite)
def test_atari_native_shape_pipeline(ray_start_shared):
    """The full Atari preprocessing pipeline at the NATIVE 210x160x3 uint8
    shape — grayscale+resize to 84x84, framestack 4, CNN module, actor
    rollout workers — executes end-to-end with finite losses."""
    from ray_tpu.rllib import PPO, PPOConfig
    from ray_tpu.rllib.connectors import atari_connectors
    from ray_tpu.rllib.env import VectorEnv

    class SyntheticAtariEnv(VectorEnv):
        """Atari-native observations (210x160x3 uint8), 4 actions."""

        n_actions = 4

        def __init__(self, n_envs: int, seed: int = 0):
            self.n_envs = n_envs
            self._rng = np.random.default_rng(seed)
            self._t = np.zeros(n_envs, dtype=np.int32)

        @property
        def obs_shape(self):
            return (210, 160, 3)

        @property
        def obs_dtype(self):
            return np.uint8

        def reset(self):
            self._t[:] = 0
            return self._obs()

        def _obs(self):
            return self._rng.integers(0, 255,
                                      (self.n_envs, *self.obs_shape),
                                      dtype=np.uint8)

        def step(self, actions):
            self._t += 1
            rewards = (np.asarray(actions) == 1).astype(np.float32)
            dones = self._t >= 32
            infos = {}
            if dones.any():
                infos["final_obs"] = self._obs()
                self._t[dones] = 0
            return self._obs(), rewards, dones, infos

    algo = PPO(PPOConfig(
        env=lambda n_envs, seed: SyntheticAtariEnv(n_envs, seed),
        connectors=atari_connectors(),
        num_rollout_workers=1,
        num_envs_per_worker=2,
        rollout_fragment_length=16,
        sgd_minibatch_size=32,
        num_sgd_iter=2,
        seed=0,
    ))
    try:
        m = algo.train()
        assert np.isfinite(m["total_loss"])
        m2 = algo.train()
        assert np.isfinite(m2["total_loss"])
        # Reward signal flows (action-1 reward on the synthetic env).
        assert m2.get("episode_reward_mean") is not None
    finally:
        algo.stop()
