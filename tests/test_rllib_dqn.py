"""DQN / replay buffers / offline IO (reference `rllib/algorithms/dqn`,
`rllib/utils/replay_buffers/`, `rllib/offline/`)."""

import numpy as np
import pytest

import ray_tpu


# --------------------------------------------------------------------------- #
# replay buffers
# --------------------------------------------------------------------------- #


def _transitions(n, start=0):
    return {
        "obs": np.arange(start, start + n, dtype=np.float32)[:, None],
        "actions": np.zeros(n, np.int64),
        "rewards": np.ones(n, np.float32),
        "dones": np.zeros(n, bool),
    }


def test_replay_buffer_ring_eviction():
    from ray_tpu.rllib import ReplayBuffer

    buf = ReplayBuffer(capacity=10)
    buf.add(_transitions(6))
    assert len(buf) == 6
    buf.add(_transitions(6, start=100))
    assert len(buf) == 10  # capped
    s = buf.sample(50)
    assert s["obs"].shape == (50, 1)
    # The two oldest rows (obs 0, 1) were evicted by the wraparound.
    assert 0.0 not in s["obs"] and 1.0 not in s["obs"]
    # A mega-batch keeps only the newest `capacity` rows.
    buf.add(_transitions(25, start=1000))
    s = buf.sample(100)
    assert s["obs"].min() >= 1015


def test_prioritized_buffer_biases_and_reweights():
    from ray_tpu.rllib import PrioritizedReplayBuffer

    buf = PrioritizedReplayBuffer(capacity=100, alpha=1.0, beta=0.5, seed=0)
    buf.add(_transitions(100))
    # Give row 7 overwhelming priority: it must dominate samples.
    prios = np.full(100, 1e-3)
    prios[7] = 10.0
    buf.update_priorities(np.arange(100), prios)
    s = buf.sample(256)
    frac_7 = float(np.mean(s["_batch_indices"] == 7))
    assert frac_7 > 0.9
    # IS weights: the over-sampled row gets the SMALLEST weight.
    w = s["weights"]
    assert w.max() <= 1.0 + 1e-6
    idx7 = s["_batch_indices"] == 7
    if idx7.any() and (~idx7).any():
        assert w[idx7].max() < w[~idx7].min()


def test_prioritized_new_samples_get_max_priority():
    from ray_tpu.rllib import PrioritizedReplayBuffer

    buf = PrioritizedReplayBuffer(capacity=100, alpha=1.0, seed=0)
    buf.add(_transitions(50))
    buf.update_priorities(np.arange(50), np.full(50, 1e-4))
    buf.add(_transitions(10, start=50))  # fresh rows at max prio (1.0)
    s = buf.sample(200)
    frac_new = float(np.mean(s["_batch_indices"] >= 50))
    assert frac_new > 0.8  # fresh rows dominate until trained on


# --------------------------------------------------------------------------- #
# DQN
# --------------------------------------------------------------------------- #


def test_dqn_learns_cartpole(ray_start_shared):
    """Learning test (reference rllib_learning_tests_*): double-DQN with
    prioritized replay reaches reward >= 100 on CartPole."""
    from ray_tpu.rllib import DQN, DQNConfig

    algo = DQN(DQNConfig(
        env="CartPole-v1",
        num_rollout_workers=1,
        num_envs_per_worker=8,
        rollout_fragment_length=32,
        buffer_capacity=50_000,
        learning_starts=1_000,
        train_batch_size=64,
        updates_per_iteration=32,
        target_network_update_freq=500,
        epsilon_timesteps=10_000,
        lr=5e-4,
        seed=0,
    ))
    best = 0.0
    try:
        for _ in range(120):
            result = algo.train()
            r = result.get("episode_reward_mean")
            if r is not None:
                best = max(best, r)
            if best >= 100:
                break
        assert best >= 100, f"DQN failed to learn CartPole: best {best}"
    finally:
        algo.stop()


def test_dqn_save_restore(ray_start_shared, tmp_path):
    from ray_tpu.rllib import DQN, DQNConfig

    cfg = dict(env="Catch-v0", num_rollout_workers=1,
               num_envs_per_worker=4, rollout_fragment_length=8,
               learning_starts=64, train_batch_size=32,
               updates_per_iteration=2, seed=0)
    algo = DQN(DQNConfig(**cfg))
    try:
        for _ in range(4):
            algo.train()
        ts = algo._timesteps
        algo.save(str(tmp_path / "ck"))
    finally:
        algo.stop()

    algo2 = DQN(DQNConfig(**cfg))
    try:
        algo2.restore(str(tmp_path / "ck"))
        assert algo2._timesteps == ts
        algo2.train()  # still trains after restore
    finally:
        algo2.stop()


# --------------------------------------------------------------------------- #
# offline IO + BC
# --------------------------------------------------------------------------- #


def test_offline_roundtrip_and_bc(ray_start_shared, tmp_path):
    """Rollouts -> write via Data layer -> read -> behavior-clone the
    expert; the clone's action agreement with the expert is high."""
    import jax

    from ray_tpu.rllib import BC, BCConfig, read_batches, write_batches
    from ray_tpu.rllib.rollout import RolloutWorker

    # A scripted "expert" for CartPole: lean into the pole's fall.
    w = RolloutWorker("CartPole-v1", n_envs=4, seed=0)
    batches = []
    for _ in range(8):
        b = w.sample(32)
        # Relabel actions with the scripted expert policy.
        b["actions"] = (b["obs"][:, 2] > 0).astype(np.int64)
        batches.append(b)

    path = str(tmp_path / "exp")
    files = write_batches(path, batches, format="json")
    assert files

    ds = read_batches(path, format="json")
    assert ds.count() == 8 * 32 * 4

    bc = BC(BCConfig(obs_dim=4, n_actions=2, lr=3e-3, seed=0))
    for _ in range(60):
        bc.train_on_dataset(ds, epochs=1, batch_size=256)
    params = bc.get_policy_weights()
    all_obs = np.concatenate([b["obs"] for b in batches])
    expert = (all_obs[:, 2] > 0).astype(np.int64)
    pred = np.asarray(bc.module.forward_inference(params, all_obs)["actions"])
    agreement = float(np.mean(pred == expert))
    assert agreement > 0.9, f"BC agreement too low: {agreement}"


def test_prioritized_mega_batch_gets_priorities():
    """A single add() larger than capacity must still assign priorities
    (regression: the early-return path skipped _on_added -> NaN probs)."""
    from ray_tpu.rllib import PrioritizedReplayBuffer

    buf = PrioritizedReplayBuffer(capacity=50, seed=0)
    buf.add(_transitions(120))
    s = buf.sample(20)
    assert s["obs"].shape == (20, 1)
    assert np.isfinite(s["weights"]).all()


def test_buffer_state_roundtrip():
    from ray_tpu.rllib import PrioritizedReplayBuffer

    buf = PrioritizedReplayBuffer(capacity=32, alpha=1.0, seed=0)
    buf.add(_transitions(20))
    buf.update_priorities(np.arange(20), np.linspace(0.1, 2.0, 20))
    buf2 = PrioritizedReplayBuffer(capacity=32, alpha=1.0, seed=1)
    buf2.set_state(buf.state())
    assert len(buf2) == 20
    np.testing.assert_array_equal(buf2._prios, buf._prios)
    s = buf2.sample(10)
    assert s["obs"].shape == (10, 1)


def test_dqn_transitions_bootstrap_truncation():
    """Truncated rows keep a bootstrap (DONES=False in the TD mask) and
    their next_obs is the TRUE final observation, not the reset obs."""
    from ray_tpu.rllib.dqn import DQN

    T, n = 3, 2
    obs = np.arange(T * n, dtype=np.float32).reshape(T * n, 1)
    batch = {
        "obs": obs.copy(),
        "_last_obs": np.array([[100.0], [101.0]], np.float32),
        "actions": np.zeros(T * n, np.int64),
        "rewards": np.ones(T * n, np.float32),
        # env row 0 truncates at t=1; env row 1 terminates at t=2
        "dones": np.array([0, 0, 1, 0, 0, 1], bool),
        "truncateds": np.array([0, 0, 1, 0, 0, 0], bool),
        "_shape": np.array([T, n]),
        # flat index of the done rows: t=1,row0 -> 1*2+0=2; t=2,row1 -> 5
        "_final_obs_at": np.array([2, 5]),
        "_final_obs": np.array([[55.0], [66.0]], np.float32),
    }
    out = DQN._transitions(None, batch)
    # Truncated row (flat idx 2): bootstraps (done False) from true final.
    assert not out["dones"][2]
    assert out["next_obs"][2, 0] == 55.0
    # Terminated row (flat idx 5): masked.
    assert out["dones"][5]
    # Ordinary row: next_obs is the time-shifted obs.
    assert out["next_obs"][0, 0] == obs[2, 0]  # t=0,row0 -> t=1,row0
    # Fragment tail without done: bootstraps from _last_obs (t=2,row0
    # flattens to index 4; _last_obs row 0 is 100.0).
    assert out["next_obs"][4, 0] == 100.0


def test_workerset_sample_replaces_dead_worker(ray_start_shared):
    """WorkerSet.sample survives a dead worker by replacing it in place —
    the fault tolerance PPO/DQN rely on."""
    from ray_tpu.rllib.rollout import WorkerSet

    ws = WorkerSet("CartPole-v1", num_workers=2, n_envs=2)
    try:
        ws.sample(4)
        ray_tpu.kill(ws.workers[0])
        frags = ws.sample(8)
        assert len(frags) == 2
        assert all(f["obs"].shape == (16, 4) for f in frags)
    finally:
        ws.shutdown()
