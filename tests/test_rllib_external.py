"""External env: policy server + client over HTTP.

Reference behavior: `rllib/env/policy_server_input.py` /
`policy_client.py` — an external simulator asks the current policy for
actions and logs rewards; the server assembles complete episodes into
trainable batches.
"""

import numpy as np
import pytest


def _make_server(explore=True):
    from ray_tpu.rllib.external import PolicyServer
    from ray_tpu.rllib.rl_module import DiscretePolicyModule, SpecDict

    module = DiscretePolicyModule(SpecDict(4, 2), hidden=(16, 16))
    return PolicyServer(module, explore=explore, seed=0)


def test_external_episode_collection():
    from ray_tpu.rllib import sample_batch as sb
    from ray_tpu.rllib.external import PolicyClient

    server = _make_server()
    try:
        client = PolicyClient(server.address)
        rng = np.random.default_rng(0)
        for _ in range(3):
            eid = client.start_episode()
            obs = rng.standard_normal(4).astype(np.float32)
            for step in range(5):
                a = client.get_action(eid, obs)
                assert a in (0, 1)
                obs = rng.standard_normal(4).astype(np.float32)
                client.log_returns(eid, 1.0)
            client.end_episode(eid, obs, terminated=True)
        batch = server.sample_batch()
        assert batch is not None
        assert batch[sb.OBS].shape == (15, 4)
        assert batch[sb.ACTIONS].shape == (15,)
        # Every step logged reward 1.0 and attribution is per transition.
        np.testing.assert_allclose(batch[sb.REWARDS], np.ones(15))
        assert batch["next_obs"].shape == (15, 4)
        # done only on the terminal transition of each episode
        assert batch[sb.DONES].sum() == 3
        assert server.episode_returns() == [5.0, 5.0, 5.0]
        # drained: next sample is empty until more episodes finish
        assert server.sample_batch() is None
    finally:
        server.stop()


def test_external_batch_trains_dqn_learner():
    """Collected external transitions are learnable (DQN TD update)."""
    from ray_tpu.rllib.dqn import DQNConfig, DQNLearner, QModule
    from ray_tpu.rllib.external import PolicyClient
    from ray_tpu.rllib.rl_module import SpecDict

    server = _make_server()
    try:
        client = PolicyClient(server.address)
        rng = np.random.default_rng(1)
        eid = client.start_episode()
        obs = rng.standard_normal(4).astype(np.float32)
        for _ in range(32):
            client.get_action(eid, obs)
            obs = rng.standard_normal(4).astype(np.float32)
            client.log_returns(eid, float(rng.random()))
        client.end_episode(eid, obs)
        batch = server.sample_batch()
        learner = DQNLearner(QModule(SpecDict(4, 2), hidden=(16, 16)),
                             DQNConfig(), seed=0)
        metrics, td = learner.update_dqn(batch)
        assert np.isfinite(metrics["td_loss"])
        assert len(td) == 32
    finally:
        server.stop()


def test_weight_sync_changes_actions():
    """Greedy actions reflect set_weights (policy updates propagate)."""
    import jax

    server = _make_server(explore=False)
    try:
        from ray_tpu.rllib.external import PolicyClient

        client = PolicyClient(server.address)
        obs = np.full(4, 0.5, np.float32)

        def greedy_action():
            eid = client.start_episode()
            a = client.get_action(eid, obs)
            client.end_episode(eid, obs)
            return a

        greedy_action()  # exercises inference with the initial weights
        # Swap in all-zero weights: zero logits argmax to action 0 —
        # proving set_weights() actually changes served actions.
        params = jax.device_get(server.params)
        zeroed = jax.tree_util.tree_map(np.zeros_like, params)
        server.set_weights(zeroed)
        assert greedy_action() == 0
    finally:
        server.stop()


def test_client_error_surfacing():
    from ray_tpu.rllib.external import PolicyClient

    server = _make_server()
    try:
        client = PolicyClient(server.address)
        with pytest.raises(Exception):
            client.get_action("nonexistent-episode", [0, 0, 0, 0])
    finally:
        server.stop()
