"""Image-observation path: connectors, CNN module, Atari-shaped training.

Mirrors the reference's connector tests (`rllib/connectors/tests/`) and the
Atari PPO tuned-example shape (`tuned_examples/ppo/atari-ppo.yaml`) on the
synthetic Catch env (ale_py is not installed in CI).
"""

import numpy as np
import pytest


def test_grayscale_resize_area_and_nearest():
    from ray_tpu.rllib.connectors import GrayscaleResize

    # Integer-factor path: area mean.
    obs = np.zeros((2, 8, 8, 3), np.uint8)
    obs[:, :4] = 255
    out = GrayscaleResize(4, 4)(obs)
    assert out.shape == (2, 4, 4) and out.dtype == np.uint8
    assert out[0, 0, 0] == 255 and out[0, 3, 0] == 0

    # Non-integer path: nearest-index sampling (Atari 210x160 -> 84x84).
    big = np.random.default_rng(0).integers(
        0, 255, (1, 210, 160, 3), dtype=np.uint8)
    out = GrayscaleResize(84, 84)(big)
    assert out.shape == (1, 84, 84)


def test_frame_stack_and_reset_rows():
    from ray_tpu.rllib.connectors import FrameStack

    fs = FrameStack(k=3)
    f1 = np.full((2, 4, 4), 1, np.uint8)
    f2 = np.full((2, 4, 4), 2, np.uint8)
    f3 = np.full((2, 4, 4), 3, np.uint8)
    assert (fs(f1)[0, 0, 0] == [1, 1, 1]).all()  # first frame repeated
    assert (fs(f2)[0, 0, 0] == [1, 1, 2]).all()
    # peek does not commit
    peeked = fs.peek(f3)
    assert (peeked[0, 0, 0] == [1, 2, 3]).all()
    assert (fs._stack[0, 0, 0] == [1, 1, 2]).all()
    # env 0 resets; env 1 continues
    fs.reset_rows(np.array([0]), f3)
    assert (fs._stack[0, 0, 0] == [3, 3, 3]).all()
    assert (fs._stack[1, 0, 0] == [1, 1, 2]).all()


def test_connector_env_stacks_and_resets():
    from ray_tpu.rllib.connectors import ConnectorPipeline, FrameStack
    from ray_tpu.rllib.env import CatchVectorEnv, ConnectorVectorEnv

    env = ConnectorVectorEnv(CatchVectorEnv(n_envs=4, seed=0, size=9),
                             ConnectorPipeline([FrameStack(4)]))
    assert env.obs_shape == (9, 9, 4)
    obs = env.reset()
    assert obs.shape == (4, 9, 9, 4) and obs.dtype == np.uint8
    # First obs: all stack slots identical.
    assert (obs[..., 0] == obs[..., 3]).all()
    steps = 0
    saw_done = False
    while steps < 30 and not saw_done:
        obs, rew, dones, infos = env.step(np.ones(4, np.int64))
        steps += 1
        if dones.any():
            saw_done = True
            i = int(np.nonzero(dones)[0][0])
            # final_obs carries pre-reset frames (continuing stack)...
            assert "final_obs" in infos
            # ...while the returned obs restarted its stack: all slots equal.
            assert (obs[i, ..., 0] == obs[i, ..., 3]).all()
    assert saw_done


def test_conv_module_shapes():
    import jax

    from ray_tpu.rllib.rl_module import ConvPolicyModule, SpecDict

    mod = ConvPolicyModule(SpecDict(0, 3, (21, 21, 4)))
    params = mod.init_params(jax.random.PRNGKey(0))
    obs = np.random.default_rng(0).integers(
        0, 255, (5, 21, 21, 4), dtype=np.uint8)
    out = mod.forward_exploration(params, obs, jax.random.PRNGKey(1))
    assert out["actions"].shape == (5,) and out["vf"].shape == (5,)
    train = mod.forward_train(params, {"obs": obs,
                                       "actions": np.asarray(out["actions"])})
    assert train["logits"].shape == (5, 3)


def test_image_rollout_worker_batch_layout():
    from ray_tpu.rllib.connectors import ConnectorPipeline, FrameStack
    from ray_tpu.rllib.rollout import RolloutWorker

    w = RolloutWorker("Catch-v0", n_envs=4, seed=0,
                      connectors=ConnectorPipeline([FrameStack(2)]))
    batch = w.sample(12)
    T, n = batch["_shape"]
    assert (T, n) == (12, 4)
    assert batch["obs"].shape == (48, 21, 21, 2)
    assert batch["obs"].dtype == np.uint8
    assert batch["_last_obs"].shape == (4, 21, 21, 2)


def test_ppo_atari_shaped_end_to_end(ray_start_shared):
    """The Atari-PPO path (CNN module + frame stacking + actor workers)
    executes end-to-end and improves on Catch."""
    from ray_tpu.rllib import PPO, PPOConfig
    from ray_tpu.rllib.connectors import ConnectorPipeline, FrameStack

    from ray_tpu.rllib.env import CatchVectorEnv

    algo = PPO(PPOConfig(
        # Shaped small Catch: the unshaped terminal-only reward needs far
        # more samples than CI affords; the path under test (CNN + frame
        # stack + uint8 batches through actor workers) is identical.
        env=lambda n_envs, seed: CatchVectorEnv(n_envs, seed, size=9,
                                                shaped=True),
        connectors=ConnectorPipeline([FrameStack(2)]),
        num_rollout_workers=2,
        num_envs_per_worker=8,
        rollout_fragment_length=40,
        num_sgd_iter=4,
        sgd_minibatch_size=256,
        lr=1e-3,
        entropy_coeff=0.01,
        seed=0,
    ))
    try:
        first, best = None, -2.0
        for _ in range(25):
            m = algo.train()
            r = m.get("episode_reward_mean")
            if r is not None:
                if first is None:
                    first = r
                best = max(best, r)
            if first is not None and best > first + 0.3:
                break
        assert first is not None
        assert best > first + 0.3, \
            f"no improvement: first={first}, best={best}"
    finally:
        algo.stop()
