"""Multi-learner (dp-sharded) LearnerGroup tests.

Reference behavior: `rllib/core/learner/learner_group.py:61,114-126`
scales the update to N workers with torch DDP; here the same scaling is
one SPMD program dp-sharded over a Mesh — these tests prove the sharded
update is numerically the SAME update (loss/params parity with the
single-device learner) on the virtual 8-device CPU mesh.
"""

import numpy as np
import pytest


def _ppo_cfg(**over):
    from ray_tpu.rllib.ppo import PPOConfig

    cfg = PPOConfig()
    for k, v in over.items():
        setattr(cfg, k, v)
    return cfg


def _ppo_batch(rng, n, obs_dim=4, n_actions=2):
    from ray_tpu.rllib import sample_batch as sb

    return {
        sb.OBS: rng.standard_normal((n, obs_dim)).astype(np.float32),
        sb.ACTIONS: rng.integers(0, n_actions, n).astype(np.int32),
        sb.LOGP: np.log(np.full(n, 1.0 / n_actions, np.float32)),
        sb.ADVANTAGES: rng.standard_normal(n).astype(np.float32),
        sb.VF_PREDS: rng.standard_normal(n).astype(np.float32),
        sb.VALUE_TARGETS: rng.standard_normal(n).astype(np.float32),
    }


def _flat_params(p):
    import jax

    return np.concatenate([np.asarray(x).ravel()
                           for x in jax.tree_util.tree_leaves(p)])


def _make_ppo_learner(num_devices=1, seed=0):
    from ray_tpu.rllib.ppo import PPOLearner
    from ray_tpu.rllib.rl_module import DiscretePolicyModule, SpecDict

    module = DiscretePolicyModule(SpecDict(4, 2), hidden=(16, 16))
    return PPOLearner(module, _ppo_cfg(), seed=seed,
                      num_devices=num_devices)


def test_ppo_update_parity_dp4():
    """update() on a dp=4 mesh matches the single-device update."""
    rng = np.random.default_rng(0)
    batches = [_ppo_batch(np.random.default_rng(i), 64) for i in range(3)]
    l1 = _make_ppo_learner(1)
    l4 = _make_ppo_learner(4)
    for b in batches:
        m1 = l1.update(b)
        m4 = l4.update(b)
        assert m1 and m4
        assert abs(m1["total_loss"] - m4["total_loss"]) < 1e-4
    np.testing.assert_allclose(_flat_params(l1.get_weights()),
                               _flat_params(l4.get_weights()),
                               rtol=1e-4, atol=1e-5)


def test_ppo_update_many_parity_dp4():
    """The scanned minibatch-epoch path matches too."""
    rng = np.random.default_rng(7)
    flat = _ppo_batch(rng, 96)
    stacked = {k: v.reshape((3, 32) + v.shape[1:]) for k, v in flat.items()}
    l1 = _make_ppo_learner(1)
    l4 = _make_ppo_learner(4)
    m1 = l1.update_many(stacked)
    m4 = l4.update_many(stacked)
    assert abs(m1["total_loss"] - m4["total_loss"]) < 1e-4
    np.testing.assert_allclose(_flat_params(l1.get_weights()),
                               _flat_params(l4.get_weights()),
                               rtol=1e-4, atol=1e-5)


def test_ppo_update_trims_ragged_batch():
    """DDP drop-last: a batch not divisible by dp trains on the largest
    divisible prefix; a batch smaller than dp is a clean no-op."""
    l4 = _make_ppo_learner(4)
    before = _flat_params(l4.get_weights())
    assert l4.update(_ppo_batch(np.random.default_rng(1), 3)) == {}
    np.testing.assert_array_equal(before, _flat_params(l4.get_weights()))
    m = l4.update(_ppo_batch(np.random.default_rng(2), 66))
    assert m and np.isfinite(m["total_loss"])


def test_impala_learner_dp_shards_env_axis():
    """IMPALA's time-major batches shard over envs (dp_axis=1): parity
    with single-device on a [T, B] fragment."""
    from ray_tpu.rllib import sample_batch as sb
    from ray_tpu.rllib.impala import IMPALAConfig, IMPALALearner
    from ray_tpu.rllib.rl_module import DiscretePolicyModule, SpecDict

    T, B, obs = 5, 8, 4
    rng = np.random.default_rng(3)
    batch = {
        sb.OBS: rng.standard_normal((T, B, obs)).astype(np.float32),
        "last_obs": rng.standard_normal((1, B, obs)).astype(np.float32),
        sb.ACTIONS: rng.integers(0, 2, (T, B)).astype(np.int32),
        sb.LOGP: np.log(np.full((T, B), 0.5, np.float32)),
        sb.REWARDS: rng.standard_normal((T, B)).astype(np.float32),
        sb.DONES: (rng.random((T, B)) < 0.1).astype(np.float32),
        "terminateds": np.zeros((T, B), np.float32),
        "behavior_next_vf": rng.standard_normal((T, B)).astype(np.float32),
    }
    cfg = IMPALAConfig()

    def make(n):
        module = DiscretePolicyModule(SpecDict(obs, 2), hidden=(16, 16))
        return IMPALALearner(module, cfg, seed=0, num_devices=n)

    l1, l4 = make(1), make(4)
    m1, m4 = l1.update(batch), l4.update(batch)
    assert abs(m1["total_loss"] - m4["total_loss"]) < 1e-4
    np.testing.assert_allclose(_flat_params(l1.get_weights()),
                               _flat_params(l4.get_weights()),
                               rtol=1e-4, atol=1e-5)


def test_dqn_learner_dp_parity():
    from ray_tpu.rllib import sample_batch as sb
    from ray_tpu.rllib.dqn import DQNConfig, DQNLearner, QModule

    n, obs = 32, 4
    rng = np.random.default_rng(5)
    batch = {
        sb.OBS: rng.standard_normal((n, obs)).astype(np.float32),
        "next_obs": rng.standard_normal((n, obs)).astype(np.float32),
        sb.ACTIONS: rng.integers(0, 2, n).astype(np.int32),
        sb.REWARDS: rng.standard_normal(n).astype(np.float32),
        sb.DONES: (rng.random(n) < 0.1).astype(np.float32),
    }
    cfg = DQNConfig()

    def make(nd):
        from ray_tpu.rllib.rl_module import SpecDict

        module = QModule(SpecDict(obs, 2), hidden=(16, 16))
        return DQNLearner(module, cfg, seed=0, num_devices=nd)

    l1, l4 = make(1), make(4)
    m1, td1 = l1.update_dqn(batch)
    m4, td4 = l4.update_dqn(batch)
    assert abs(m1["td_loss"] - m4["td_loss"]) < 1e-4
    np.testing.assert_allclose(td1, td4, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(_flat_params(l1.get_weights()),
                               _flat_params(l4.get_weights()),
                               rtol=1e-4, atol=1e-5)


def test_ppo_e2e_num_learners(ray_start_shared):
    """Whole-algorithm smoke: PPO trains with a dp=2 sharded learner."""
    from ray_tpu.rllib.ppo import PPOConfig

    cfg = (PPOConfig()
           .environment("CartPole-v1")
           .rollouts(num_rollout_workers=1, num_envs_per_worker=4,
                     rollout_fragment_length=32)
           .training(num_sgd_iter=2, sgd_minibatch_size=64))
    cfg.num_learners = 2
    algo = cfg.build()
    try:
        res = algo.train()
        assert np.isfinite(res["total_loss"])
        assert res["sgd_steps"] > 0
    finally:
        algo.stop()


def test_sharded_group_split():
    from ray_tpu.rllib.learner import _ShardedLearnerGroup

    batch = {"a": np.arange(10), "b": np.arange(20).reshape(10, 2)}
    parts = _ShardedLearnerGroup._split(batch, 2, 0)
    assert len(parts) == 2
    np.testing.assert_array_equal(parts[0]["a"], np.arange(5))
    np.testing.assert_array_equal(parts[1]["a"], np.arange(5, 10))
    tm = {"x": np.arange(24).reshape(2, 4, 3)}
    parts = _ShardedLearnerGroup._split(tm, 2, 1)
    assert parts[0]["x"].shape == (2, 2, 3)
    np.testing.assert_array_equal(parts[1]["x"], tm["x"][:, 2:])


def test_remote_sharded_group_trains_multiprocess(ray_start_regular):
    """mode='remote' num_learners=2: two learner ACTORS (separate OS
    processes) form a jax.distributed group and run one SPMD dp-sharded
    update — the multi-host path (reference learner_group.py:114-126
    N-worker scaling), exercisable on CPU since workers stopped loading
    the host's accelerator plugin. The sharded update's loss must agree
    with a single local learner on the same batch (same global batch, dp
    gradient psum) — a guard against N silently-independent learners."""
    from ray_tpu.rllib.learner import LearnerGroup

    rng = np.random.default_rng(0)
    batch = _ppo_batch(rng, 64)

    def _skip_if_unsupported_env(e: Exception):
        # Some jax builds cannot form a multiprocess computation group on
        # the CPU backend at all ("Multiprocess computations aren't
        # implemented on the CPU backend") — an environment limitation,
        # not a framework regression: skip instead of failing tier-1.
        if "Multiprocess computations aren't implemented" in str(e):
            pytest.skip("jax CPU backend does not support multiprocess "
                        "computations in this environment")

    group = None
    try:
        try:
            group = LearnerGroup(lambda **kw: _make_ppo_learner(**kw),
                                 mode="remote", num_learners=2)
            out = group.update(batch)
        except Exception as e:  # noqa: BLE001 — env-capability probe
            _skip_if_unsupported_env(e)
            raise
        assert np.isfinite(out["total_loss"])
        single = _make_ppo_learner(num_devices=1).update(batch)
        assert abs(out["total_loss"] - single["total_loss"]) < 0.05, \
            (out["total_loss"], single["total_loss"])
    finally:
        if group is not None:
            group.shutdown()
