"""Runtime env pip/venv plugin: offline install from a local wheelhouse.

Reference behavior: `python/ray/_private/runtime_env/pip.py` builds a
virtualenv per runtime_env and runs workers inside it; here the venv is
built offline (`--no-index --find-links <wheelhouse>`), cached by
content hash, and activated via sys.path before any user import. The
test builds its own trivial wheel (a wheel is just a zip) so nothing is
fetched from any index.
"""

import os
import zipfile

import pytest


def _make_wheel(wheelhouse: str, name: str = "rtpkg", version: str = "1.0",
                value: int = 123) -> str:
    os.makedirs(wheelhouse, exist_ok=True)
    whl = os.path.join(wheelhouse, f"{name}-{version}-py3-none-any.whl")
    dist = f"{name}-{version}.dist-info"
    with zipfile.ZipFile(whl, "w") as zf:
        zf.writestr(f"{name}/__init__.py", f"VALUE = {value}\n")
        zf.writestr(f"{dist}/METADATA",
                    f"Metadata-Version: 2.1\nName: {name}\n"
                    f"Version: {version}\n")
        zf.writestr(f"{dist}/WHEEL",
                    "Wheel-Version: 1.0\nGenerator: test\nRoot-Is-Purelib:"
                    " true\nTag: py3-none-any\n")
        zf.writestr(f"{dist}/RECORD", "")
    return whl


def test_normalize_and_hash(tmp_path):
    from ray_tpu.core.runtime_env import _normalize_pip, pip_env_hash

    wh = str(tmp_path / "wheels")
    _make_wheel(wh)
    env = {"pip": ["rtpkg"], "pip_wheelhouse": wh}
    _normalize_pip(env)
    assert env["pip"]["packages"] == ["rtpkg"]
    assert env["pip"]["wheelhouse"] == wh
    assert "pip_wheelhouse" not in env
    h1 = env["pip"]["env_hash"]
    assert h1 == pip_env_hash(env["pip"])
    # Adding a wheel changes the hash (stale venvs/workers never reused).
    _make_wheel(wh, name="other")
    assert pip_env_hash(env["pip"]) != h1

    with pytest.raises(ValueError, match="wheelhouse"):
        _normalize_pip({"pip": ["rtpkg"]})
    with pytest.raises(ValueError, match="not a directory"):
        _normalize_pip({"pip": ["x"], "pip_wheelhouse": "/nope/nope"})


def test_pip_env_installs_in_worker(tmp_path):
    """A task with a pip runtime_env imports the wheel's package; a task
    without it cannot (worker-pool isolation by env marker)."""
    import ray_tpu

    wh = str(tmp_path / "wheels")
    _make_wheel(wh, value=777)
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote(runtime_env={"pip": ["rtpkg"],
                                     "pip_wheelhouse": wh})
        def with_pkg():
            import rtpkg

            return rtpkg.VALUE, os.environ.get("VIRTUAL_ENV", "")

        @ray_tpu.remote
        def without_pkg():
            try:
                import rtpkg  # noqa: F401

                return "importable"
            except ImportError:
                return "missing"

        value, venv = ray_tpu.get(with_pkg.remote(), timeout=120)
        assert value == 777
        assert "venv-" in venv
        assert ray_tpu.get(without_pkg.remote(), timeout=60) == "missing"

        # Second task with the same env hits the cached venv (same dir).
        _, venv2 = ray_tpu.get(with_pkg.remote(), timeout=120)
        assert venv2 == venv
    finally:
        ray_tpu.shutdown()


def test_pip_env_missing_package_fails_loudly(tmp_path):
    import ray_tpu

    wh = str(tmp_path / "wheels")
    _make_wheel(wh)
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote(runtime_env={"pip": ["no-such-package"],
                                     "pip_wheelhouse": wh})
        def f():
            return 1

        with pytest.raises(Exception,
                           match="runtime_env setup failed|pip install"):
            ray_tpu.get(f.remote(), timeout=120)
    finally:
        ray_tpu.shutdown()
