"""SegmentPool: warm shm segment recycling across puts.

Reference behavior: plasma's arena keeps object memory warm across
create/seal cycles (`src/ray/object_manager/plasma/store_runner.h:56`);
here per-object segments are recycled by renaming the /dev/shm file back
into an owner-side pool once the last reference drops.
"""

import time

import numpy as np
import pytest


def _wait_for(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def test_put_recycles_segments(ray_start_regular):
    """put -> free -> put of the same size reuses the warm segment and
    round-trips the new data exactly."""
    import ray_tpu

    pool = ray_tpu._global_runtime._segment_pool
    assert pool.enabled
    arrs = [np.full(512 * 1024, float(i)) for i in range(4)]
    for i, arr in enumerate(arrs):
        ref = ray_tpu.put(arr)
        back = ray_tpu.get(ref)
        np.testing.assert_array_equal(back, arr)
        del back, ref
        # Free flushes immediately for pool-tracked puts; reclaim happens
        # on the flush response.
        _wait_for(lambda: pool._bytes > 0)
        if i > 0:
            assert pool._bytes > 0, "freed segment did not enter the pool"


def test_live_view_blocks_recycling(ray_start_regular):
    """A zero-copy view that outlives its ref must keep its segment out
    of the pool — the next same-size put gets fresh memory and the held
    view's data stays intact."""
    import ray_tpu

    pool = ray_tpu._global_runtime._segment_pool
    sentinel = np.full(256 * 1024, 7.0)
    ref = ray_tpu.put(sentinel)
    held = ray_tpu.get(ref)   # zero-copy view into the segment
    del ref                   # refcount 0 -> free -> reclaim attempt
    time.sleep(0.3)
    other = ray_tpu.put(np.full(256 * 1024, 9.0))
    got = ray_tpu.get(other)
    np.testing.assert_array_equal(held, sentinel)  # never overwritten
    np.testing.assert_array_equal(got, np.full(256 * 1024, 9.0))


def test_recycled_object_readable_by_worker(ray_start_regular):
    """An object written into a recycled segment is readable from a
    worker process (attach-by-name still resolves post-rename)."""
    import ray_tpu

    @ray_tpu.remote
    def total(x):
        return float(x.sum())

    warm = ray_tpu.put(np.ones(512 * 1024))
    assert ray_tpu.get(total.remote(warm)) == 512 * 1024
    del warm
    time.sleep(0.3)
    arr = np.arange(512 * 1024, dtype=np.float64)
    ref = ray_tpu.put(arr)   # likely lands in the recycled segment
    assert ray_tpu.get(total.remote(ref)) == pytest.approx(float(arr.sum()))


def test_pool_respects_byte_cap(ray_start_regular):
    import ray_tpu

    pool = ray_tpu._global_runtime._segment_pool
    cap = pool._max
    big = np.zeros((cap // 8) + 4096)  # one segment larger than the cap
    ref = ray_tpu.put(big)
    ray_tpu.get(ref)
    del ref
    time.sleep(0.5)
    assert pool._bytes <= cap


def test_mt_memmove_fallback_correct():
    """The compiler-free threaded gather produces byte-identical output."""
    from ray_tpu._native import _memmove_gather_mt

    rng = np.random.default_rng(0)
    parts = [rng.integers(0, 255, n, dtype=np.uint8).tobytes()
             for n in (3, 9 * 1024 * 1024, 17, 5 * 1024 * 1024)]
    total = sum(len(p) for p in parts)
    dst = bytearray(total)
    n = _memmove_gather_mt(memoryview(dst), [memoryview(p) for p in parts],
                           total)
    assert n == total
    assert bytes(dst) == b"".join(parts)
