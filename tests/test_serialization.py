"""Serialization: pickle-5 out-of-band buffers, zero-copy, exceptions."""

import numpy as np
import pytest

from ray_tpu.core import serialization
from ray_tpu.exceptions import RayTaskError


def roundtrip(v, zero_copy=True):
    blob = serialization.serialize_to_bytes(v)
    return serialization.deserialize(blob, zero_copy=zero_copy)


def test_scalars_and_containers():
    for v in [1, 2.5, "hi", b"raw", None, True, [1, 2], {"a": (1, 2)}, {1, 2}]:
        assert roundtrip(v) == v


def test_numpy_out_of_band():
    x = np.arange(10000, dtype=np.float64).reshape(100, 100)
    y = roundtrip(x)
    np.testing.assert_array_equal(x, y)
    parts = serialization.serialize(x)
    # large array must travel out-of-band, not in the pickle stream
    assert serialization.serialized_size(parts) < x.nbytes + 2000
    assert any(isinstance(p, memoryview) and p.nbytes == x.nbytes for p in parts)


def test_zero_copy_view():
    x = np.arange(1000, dtype=np.int64)
    blob = serialization.serialize_to_bytes(x)
    view = memoryview(blob)
    y = serialization.deserialize(view, zero_copy=True)
    np.testing.assert_array_equal(x, y)


def test_mixed_structure():
    v = {"weights": np.ones((64, 64), dtype=np.float32), "step": 3,
         "names": ["a", "b"]}
    out = roundtrip(v)
    assert out["step"] == 3
    np.testing.assert_array_equal(out["weights"], v["weights"])


def test_exception_roundtrip():
    try:
        raise ValueError("kaboom")
    except ValueError as e:
        blob = serialization.serialize_exception(e, "myfn")
    err = serialization.deserialize_exception(blob)
    assert isinstance(err, RayTaskError)
    assert "kaboom" in err.traceback_str
    typed = err.as_instanceof_cause()
    assert isinstance(typed, ValueError)
    with pytest.raises(ValueError):
        raise typed


def test_unpicklable_exception_fallback():
    class Weird(Exception):
        def __reduce__(self):
            raise TypeError("cannot pickle me")

    try:
        raise Weird("odd")
    except Weird as e:
        blob = serialization.serialize_exception(e, "f")
    err = serialization.deserialize_exception(blob)
    assert isinstance(err, RayTaskError)
