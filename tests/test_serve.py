"""Serve layer: deploy/route/batch/autoscale/HTTP round-trip.

Mirrors the reference's serve test strategy (`serve/tests/` —
test_deployment_state for reconcile, test_autoscaling_policy for scaling,
plus e2e HTTP tests) at the scale of one in-process cluster.
"""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture()
def serve_cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_deploy_and_call(serve_cluster):
    @serve.deployment(num_replicas=2)
    class Echo:
        def __init__(self, prefix):
            self._prefix = prefix

        def __call__(self, payload):
            return f"{self._prefix}:{payload}"

    handle = serve.run(Echo.bind("echo"))
    results = ray_tpu.get([handle.remote(i) for i in range(8)])
    assert results == [f"echo:{i}" for i in range(8)]

    st = serve.status()
    assert st["Echo"]["target"] == 2
    assert len(st["Echo"]["replicas"]) == 2


def test_function_deployment_and_methods(serve_cluster):
    @serve.deployment
    def double(payload):
        return payload * 2

    handle = serve.run(double.bind())
    assert ray_tpu.get(handle.remote(21)) == 42

    @serve.deployment
    class Multi:
        def __call__(self, x):
            return ("call", x)

        def other(self, x):
            return ("other", x)

    h2 = serve.run(Multi.bind())
    assert ray_tpu.get(h2.remote(1)) == ("call", 1)
    assert ray_tpu.get(h2.other.remote(2)) == ("other", 2)


def test_batching_collects_concurrent_requests(serve_cluster):
    @serve.deployment(max_concurrent_queries=16)
    class Batcher:
        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.1)
        async def __call__(self, items):
            # Return the batch size each item rode in — proof of batching.
            return [len(items)] * len(items)

    handle = serve.run(Batcher.bind())
    refs = [handle.remote(i) for i in range(8)]
    sizes = ray_tpu.get(refs)
    # At least some requests must have shared a batch.
    assert max(sizes) > 1


def test_replica_failure_recovers(serve_cluster):
    @serve.deployment(num_replicas=1)
    class Fragile:
        def __call__(self, payload):
            return payload

        def pid(self, _=None):
            import os

            return os.getpid()

    handle = serve.run(Fragile.bind())
    pid = ray_tpu.get(handle.pid.remote(None))

    # Kill the replica out from under the controller.
    replica = ray_tpu.get_actor("SERVE_REPLICA::Fragile#0",
                                namespace="serve")
    ray_tpu.kill(replica)

    # The controller's health check must replace it and serving resume.
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            new_pid = ray_tpu.get(handle.pid.remote(None), timeout=5.0)
            if new_pid != pid:
                break
        except Exception:
            time.sleep(0.2)
    else:
        pytest.fail("replica was not replaced after kill")


def test_autoscaling_up_and_down(serve_cluster):
    @serve.deployment(
        max_concurrent_queries=2,
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1, max_replicas=3, target_ongoing_requests=1.0,
            upscale_delay_s=0.2, downscale_delay_s=1.0),
    )
    class Slow:
        def __call__(self, payload):
            time.sleep(0.4)
            return payload

    handle = serve.run(Slow.bind())
    assert serve.status()["Slow"]["target"] == 1

    # Sustained pressure: many concurrent requests -> scale up.
    refs = [handle.remote(i) for i in range(16)]
    deadline = time.time() + 20
    scaled_up = False
    while time.time() < deadline:
        if serve.status()["Slow"]["target"] > 1:
            scaled_up = True
            break
        time.sleep(0.1)
    assert scaled_up, "deployment did not scale up under load"
    ray_tpu.get(refs)

    # Idle -> back down to min_replicas.
    deadline = time.time() + 20
    while time.time() < deadline:
        if serve.status()["Slow"]["target"] == 1:
            break
        time.sleep(0.2)
    else:
        pytest.fail("deployment did not scale back down when idle")


def test_http_proxy_round_trip(serve_cluster):
    @serve.deployment(num_replicas=2, route_prefix="/math")
    class Adder:
        def __call__(self, payload):
            return {"sum": payload["a"] + payload["b"]}

    serve.run(Adder.bind())
    port = serve.http_port()

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/math",
        data=json.dumps({"a": 2, "b": 3}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        body = json.loads(resp.read())
    assert body == {"result": {"sum": 5}}

    # Unknown route -> 404.
    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/nope", timeout=30)
        pytest.fail("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_handle_composition_between_deployments(serve_cluster):
    @serve.deployment
    class Inner:
        def __call__(self, x):
            return x + 1

    @serve.deployment
    class Outer:
        def __init__(self, inner):
            self._inner = inner

        def __call__(self, x):
            return ray_tpu.get(self._inner.remote(x)) * 10

    serve.run(Inner.bind())
    outer = serve.run(Outer.bind(serve.get_deployment_handle("Inner")))
    assert ray_tpu.get(outer.remote(4)) == 50


def test_gpt2_sampler_deployment_batches(serve_cluster):
    from ray_tpu.serve.examples import GPT2Sampler

    # Generous deploy budget: replica __init__ jit-compiles a tiny GPT-2,
    # which can exceed the 60s default when the host is loaded (this test
    # flaked twice in contended full-suite runs).
    handle = serve.run(GPT2Sampler.bind("tiny", 64, 4), timeout_s=180.0)
    refs = [handle.remote({"ids": [1, 2, 3 + i], "max_new_tokens": 4})
            for i in range(8)]
    outs = ray_tpu.get(refs)
    for i, out in enumerate(outs):
        assert out["ids"][:3] == [1, 2, 3 + i]
        assert len(out["ids"]) > 3
    m = ray_tpu.get(handle.metrics.remote(None))
    assert m["batches_served"] >= 1
    assert m["mean_batch_size"] > 1.0, "batching never engaged"


def test_deployment_graph_composition(serve_cluster):
    """Bound deployments as init args deploy as a graph (children first)
    and arrive as live DeploymentHandles (reference deployment graphs)."""

    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return 2 * x

    @serve.deployment
    class Adder:
        def __init__(self, offset):
            self.offset = offset

        def __call__(self, x):
            return x + self.offset

    @serve.deployment
    class Driver:
        def __init__(self, doubler, adder):
            self.doubler = doubler
            self.adder = adder

        def __call__(self, x):
            d = ray_tpu.get(self.doubler.remote(x))
            return ray_tpu.get(self.adder.remote(d))

    handle = serve.run(Driver.bind(Doubler.bind(), Adder.bind(100)))
    assert ray_tpu.get(handle.remote(7)) == 114
    # Name collision across distinct bindings is rejected.
    with pytest.raises(ValueError):
        serve.run(Driver.options(name="D2").bind(
            Adder.bind(1), Adder.bind(2)))
    # Container-nested bindings (a LIST of bound models) deploy too.
    @serve.deployment
    class Ensemble:
        def __init__(self, models):
            self.models = models

        def __call__(self, x):
            return sum(ray_tpu.get(m.remote(x)) for m in self.models)

    ens = serve.run(Ensemble.bind([
        Adder.options(name="AddA").bind(1),
        Adder.options(name="AddB").bind(2)]))
    assert ray_tpu.get(ens.remote(10)) == 23


def test_graph_init_args_pass_through_untouched(serve_cluster):
    """Init args with no nested bindings keep their exact types (dict
    subclasses included); bindings hidden in sets fail loudly at deploy
    time instead of reaching the replica as inert pickled data."""
    import collections

    @serve.deployment
    class KeepsDefaultDict:
        def __init__(self, counts):
            self.counts = counts

        def __call__(self, key):
            self.counts[key].append(1)
            return len(self.counts[key])

    dd = collections.defaultdict(list)
    h = serve.run(KeepsDefaultDict.bind(dd))
    assert ray_tpu.get(h.remote("a")) == 1
    assert ray_tpu.get(h.remote("a")) == 2  # default_factory survived

    @serve.deployment
    class Adder:
        def __init__(self, n):
            self.n = n

        def __call__(self, x):
            return x + self.n

    @serve.deployment
    class SetEnsemble:
        def __init__(self, models):
            self.models = models

    with pytest.raises(ValueError, match="un-substituted"):
        serve.run(SetEnsemble.bind({Adder.bind(1), Adder.bind(2)}))


def test_schema_build_validate_deploy(serve_cluster, tmp_path):
    """serve.build -> edit -> deploy_config round trip (reference
    serve build / REST deploy), with per-deployment overrides applied."""
    import serve_app_mod

    from ray_tpu.serve.schema import build, deploy_config, validate_config

    cfg = build(serve_app_mod.app)
    deps = {d["name"] for d in cfg["applications"][0]["deployments"]}
    assert deps == {"Doubler", "Pipeline"}

    config = {
        "applications": [{
            "name": "default",
            "import_path": "serve_app_mod:app",
            "deployments": [
                {"name": "Doubler", "num_replicas": 2,
                 "max_concurrent_queries": 16},
            ],
        }],
    }
    validate_config(config)
    handle = deploy_config(config)
    assert ray_tpu.get(handle.remote(10)) == 25  # 2*10 + 5

    st = serve.status()
    assert st["Doubler"]["target"] == 2  # override applied
    # The module-level objects were not mutated by the override.
    assert serve_app_mod.Doubler.config.num_replicas == 1

    with pytest.raises(ValueError, match="unknown deployment option"):
        validate_config({"applications": [{
            "import_path": "serve_app_mod:app",
            "deployments": [{"name": "Doubler", "replicas": 2}]}]})
    with pytest.raises(ValueError, match="import_path"):
        validate_config({"applications": [{"name": "x"}]})


def test_serve_cli_deploy_and_status(serve_cluster, tmp_path):
    """The serve CLI deploys from YAML against a running cluster."""
    import yaml

    from ray_tpu.scripts.cli import main

    cfg = {"applications": [{"name": "default",
                             "import_path": "serve_app_mod:app"}]}
    path = str(tmp_path / "serve.yaml")
    with open(path, "w") as f:
        yaml.safe_dump(cfg, f)
    addr = ray_tpu._require_runtime().gcs.address
    main(["--address", f"{addr[0]}:{addr[1]}", "serve", "deploy", path])
    handle = serve.get_deployment_handle("Pipeline")
    assert ray_tpu.get(handle.remote(1)) == 7


def test_controller_crash_recovery(serve_cluster):
    """The controller's state lives in the GCS KV: killing the controller
    actor and touching the API again rebuilds deployments and re-adopts
    (or respawns) replicas without redeploying (reference controller.py:75
    checkpointed state + kv_store.py)."""
    from ray_tpu.serve import _get_or_create_controller

    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, payload):
            return f"echo:{payload}"

    handle = serve.run(Echo.bind())
    assert ray_tpu.get(handle.remote("a")) == "echo:a"

    controller = _get_or_create_controller(create=False)
    ray_tpu.kill(controller)
    time.sleep(1.0)

    # Any API touch creates a fresh controller which restores from the KV.
    deadline = time.monotonic() + 90
    status = {}
    while time.monotonic() < deadline:
        try:
            status = serve.status()
            reps = status.get("Echo", {}).get("replicas", {})
            if sum(1 for s in reps.values() if s == "RUNNING") >= 2:
                break
        except Exception:
            pass
        time.sleep(0.5)
    reps = status.get("Echo", {}).get("replicas", {})
    assert sum(1 for s in reps.values() if s == "RUNNING") >= 2, status

    # And traffic flows again through a fresh handle.
    h2 = serve.get_deployment_handle("Echo")
    assert ray_tpu.get(h2.remote("b"), timeout=60) == "echo:b"


def test_per_node_proxies_and_replacement():
    """EveryNode proxy placement: one managed proxy per alive node,
    health-checked and replaced when killed (reference http_state.py:110
    HTTPProxyStateManager)."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.serve import _get_or_create_controller
    from ray_tpu.serve.controller import SERVE_NAMESPACE

    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    cluster.add_node(num_cpus=4)
    cluster.wait_for_nodes()
    cluster.connect()
    try:
        serve.start(http_port=0, proxy_location="EveryNode")

        @serve.deployment(num_replicas=1)
        class Hello:
            def __call__(self, payload):
                return "hi"

        serve.run(Hello.bind())
        controller = _get_or_create_controller(create=False)

        def proxy_view(min_alive, timeout=60):
            deadline = time.monotonic() + timeout
            view = {}
            while time.monotonic() < deadline:
                view = ray_tpu.get(controller.proxy_status.remote(),
                                   timeout=30)
                if sum(1 for v in view.values() if v["alive"]) >= min_alive:
                    return view
                time.sleep(0.5)
            return view

        view = proxy_view(2)
        alive = [v for v in view.values() if v["alive"]]
        assert len(alive) == 2, view
        # Each proxy serves HTTP on its own port.
        for v in alive:
            url = f"http://127.0.0.1:{v['port']}/Hello"
            req = urllib.request.Request(url, data=json.dumps("x").encode(),
                                         headers={"Content-Type":
                                                  "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.read().decode() == "hi"

        # Kill one managed proxy: the controller replaces it.
        victim_node = next(iter(view))
        victim = ray_tpu.get_actor(f"SERVE_PROXY::{victim_node[:16]}",
                                   namespace=SERVE_NAMESPACE)
        ray_tpu.kill(victim)
        view2 = proxy_view(2, timeout=90)
        assert sum(1 for v in view2.values() if v["alive"]) == 2, view2
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        cluster.shutdown()


def test_batch_queue_stop_fails_pending_and_cancels_flusher():
    """Satellite: _BatchQueue.stop() must cancel the flusher task and
    fail every parked future — queued AND mid-batch — instead of leaking
    them past replica shutdown."""
    import asyncio

    from ray_tpu.serve.batching import _BatchQueue

    async def main():
        started = asyncio.Event()
        release = asyncio.Event()

        async def fn(items):
            started.set()
            await release.wait()
            return items

        q = _BatchQueue(fn, max_batch_size=2, batch_wait_timeout_s=10.0)
        t1 = asyncio.ensure_future(q.submit(1))
        t2 = asyncio.ensure_future(q.submit(2))
        await started.wait()           # flusher is mid-batch, parked in fn
        flusher = q._flusher
        assert q.stop() == 2
        with pytest.raises(RuntimeError, match="shut down"):
            await t1
        with pytest.raises(RuntimeError, match="shut down"):
            await t2
        for _ in range(5):             # let the cancellation land
            await asyncio.sleep(0)
        assert flusher.done()
        # A stopped queue refuses new work instead of parking it forever.
        with pytest.raises(RuntimeError, match="stopped"):
            await q.submit(3)

    asyncio.run(main())


def test_replica_teardown_stops_batch_queue_and_runs_shutdown_hook():
    """prepare_shutdown tears down user-side resources: batch queues are
    stopped (their parked callers fail fast) and __serve_shutdown__ runs."""
    import asyncio

    from ray_tpu.serve.replica import Replica

    events = []

    class User:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=30.0)
        async def __call__(self, items):
            return items

        def __serve_shutdown__(self):
            events.append("shutdown")

    async def main():
        rep = Replica("D", User, (), {})
        task = asyncio.ensure_future(
            rep.handle_request("__call__", (1,), {}))
        await asyncio.sleep(0.05)      # flusher parked in its batch wait
        await rep.prepare_shutdown(timeout_s=0.2)
        with pytest.raises(RuntimeError, match="shut down"):
            await task
        assert events == ["shutdown"]

    asyncio.run(main())
