"""Serve ASGI ingress + streaming responses.

Reference behavior: `serve.ingress(fastapi_app)` routes HTTP through the
app (`python/ray/serve/api.py`), proxies speak ASGI to replicas
(`serve/_private/http_proxy.py:355`), and streaming responses /
generator deployments stream chunks to the client.
"""

import json
import urllib.request

import pytest


@pytest.fixture()
def serve_cluster():
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield serve
    serve.shutdown()
    ray_tpu.shutdown()


def _post(port, path, payload=None, method="POST"):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method=method,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, dict(resp.headers), resp.read()


async def _mini_asgi(scope, receive, send):
    """Hand-rolled ASGI3 app: routes, echo, custom status/headers,
    streaming — what FastAPI would emit, without the dependency."""
    assert scope["type"] == "http"
    path = scope["path"]
    body = b""
    while True:
        event = await receive()
        if event["type"] != "http.request":
            break
        body += event.get("body") or b""
        if not event.get("more_body"):
            break
    if path == "/hello":
        # ASGI spec: header names arrive lowercased regardless of the
        # client's casing.
        names = [k for k, _ in scope["headers"]]
        assert all(k == k.lower() for k in names), names
        await send({"type": "http.response.start", "status": 200,
                    "headers": [(b"content-type", b"application/json"),
                                (b"x-app", b"mini")]})
        await send({"type": "http.response.body",
                    "body": json.dumps({"hello": "world"}).encode()})
    elif path == "/echo":
        await send({"type": "http.response.start", "status": 201,
                    "headers": [(b"content-type", b"application/json")]})
        await send({"type": "http.response.body",
                    "body": json.dumps(
                        {"echo": json.loads(body or b"null"),
                         "method": scope["method"],
                         "query": scope["query_string"].decode()}).encode()})
    elif path == "/stream":
        await send({"type": "http.response.start", "status": 200,
                    "headers": [(b"content-type", b"text/plain")]})
        for i in range(5):
            await send({"type": "http.response.body",
                        "body": f"part{i};".encode(), "more_body": True})
        await send({"type": "http.response.body", "body": b"done"})
    else:
        await send({"type": "http.response.start", "status": 404,
                    "headers": []})
        await send({"type": "http.response.body", "body": b"nope"})


def test_asgi_ingress_routes(serve_cluster):
    import ray_tpu
    from ray_tpu import serve

    @serve.deployment
    @serve.ingress(_mini_asgi)
    class Api:
        pass

    serve.run(Api.bind())
    port = serve.http_port()
    status, headers, body = _post(port, "/Api/hello", method="GET")
    assert status == 200
    assert headers.get("x-app") == "mini"
    assert json.loads(body) == {"hello": "world"}

    status, _, body = _post(port, "/Api/echo?k=v", {"n": 42})
    assert status == 201
    out = json.loads(body)
    assert out["echo"] == {"n": 42}
    assert out["method"] == "POST"
    assert out["query"] == "k=v"

    status404 = None
    try:
        _post(port, "/Api/missing", method="GET")
    except urllib.error.HTTPError as e:
        status404 = e.code
    assert status404 == 404


def test_asgi_streaming_response(serve_cluster):
    from ray_tpu import serve

    @serve.deployment
    @serve.ingress(_mini_asgi)
    class Api:
        pass

    serve.run(Api.bind())
    port = serve.http_port()
    _, _, body = _post(port, "/Api/stream", method="GET")
    assert body == b"part0;part1;part2;part3;part4;done"


def test_asgi_factory_with_instance_state(serve_cluster):
    from ray_tpu import serve

    def make_app(instance):
        async def app(scope, receive, send):
            await receive()
            await send({"type": "http.response.start", "status": 200,
                        "headers": [(b"content-type", b"text/plain")]})
            await send({"type": "http.response.body",
                        "body": str(instance.counter).encode()})
        return app

    @serve.deployment
    @serve.ingress(make_app)
    class Stateful:
        def __init__(self):
            self.counter = 17

    serve.run(Stateful.bind())
    port = serve.http_port()
    _, _, body = _post(port, "/Stateful/", method="GET")
    assert body == b"17"


def test_generator_deployment_streams_over_http(serve_cluster):
    from ray_tpu import serve

    @serve.deployment
    class Tokens:
        def __call__(self, payload):
            for i in range(int(payload["n"])):
                yield f"tok{i} "

    serve.run(Tokens.bind())
    port = serve.http_port()
    _, _, body = _post(port, "/Tokens", {"n": 4})
    assert body == b"tok0 tok1 tok2 tok3 "


def test_handle_streaming_iterator(serve_cluster):
    import ray_tpu
    from ray_tpu import serve

    @serve.deployment
    class Gen:
        def __call__(self, n):
            for i in range(n):
                yield i * i

        async def agen(self, n):
            for i in range(n):
                yield i + 100

    handle = serve.run(Gen.bind())
    items = list(handle.options(stream=True).remote(5))
    assert items == [0, 1, 4, 9, 16]
    items = list(handle.options(stream=True).method("agen").remote(3))
    assert items == [100, 101, 102]
    # A streamed handle keeps streaming through attribute access.
    assert list(handle.options(stream=True).agen.remote(2)) == [100, 101]
    # Non-streaming handle still returns a plain awaitable ref whose
    # value is the stream marker, not an iterator.
    ref = handle.remote(1)
    marker = ray_tpu.get(ref)
    assert isinstance(marker, dict) and "__serve_stream__" in marker


def test_handle_stream_on_non_generator(serve_cluster):
    from ray_tpu import serve

    @serve.deployment
    class Plain:
        def __call__(self, x):
            return x + 1

    handle = serve.run(Plain.bind())
    assert list(handle.options(stream=True).remote(5)) == [6]
