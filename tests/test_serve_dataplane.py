"""Serve fast data plane: raw frames, coalescing, locality routing,
retry-once churn semantics, and scale-to-zero (ISSUE 8).

Strategy mirrors the serve suite: frame/pick logic unit-tested directly
(deterministic), the wire path proven end to end on an in-process
cluster with the proxy's own counters as the zero-pickle witness.
"""

import asyncio
import concurrent.futures
import json
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve import dataplane


@pytest.fixture()
def serve_cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _post(port, path, payload, timeout=30):
    data = payload if isinstance(payload, (bytes, bytearray)) \
        else json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, resp.read()


def _proxy_counters():
    proxy = ray_tpu.get_actor("SERVE_PROXY", namespace="serve")
    return ray_tpu.get(proxy.counters.remote(), timeout=10)


# ------------------------------------------------------------------ codec


def test_frame_codec_roundtrip():
    meta = {"v": 1, "reqs": [{"k": "http", "n": 3}, {"k": "call", "n": 0},
                             {"k": "http", "n": 5}]}
    parts = dataplane.encode_frame(meta, [b"abc", b"hello"])
    buf = b"".join(bytes(p) for p in parts)
    out_meta, region = dataplane.decode_frame(buf)
    assert out_meta == meta
    bodies = dataplane.slice_bodies(region,
                                    [r["n"] for r in out_meta["reqs"]])
    assert [bytes(b) for b in bodies] == [b"abc", b"", b"hello"]


def test_error_frame_roundtrip():
    buf = b"".join(bytes(p) for p in
                   dataplane.encode_error_frame(ValueError("boom")))
    meta, region = dataplane.decode_frame(buf)
    assert meta["err"] == "ValueError: boom"
    assert region.nbytes == 0


# --------------------------------------------------------- pick semantics


class _Handle:
    def __init__(self, name):
        self.name = name


def _router_with(entry, local_node="node-a"):
    from ray_tpu.serve.router import Router

    router = Router.__new__(Router)  # no threads, no controller
    router._local_node = local_node
    router._inflight = {}
    return router, entry


def test_pick_prefers_colocated_pack_first():
    entry = {"max_concurrent_queries": 2,
             "replicas": [("a", _Handle("a")), ("b", _Handle("b")),
                          ("c", _Handle("c"))],
             "nodes": {"a": "node-a", "b": "node-a", "c": "node-b"},
             "depths": {}}
    router, entry = _router_with(entry)
    first = router._pick(entry)
    assert first[2] is True  # co-located
    router._inflight[first[0]] = 1
    second = router._pick(entry)
    # Pack-first: the loaded co-located replica wins while under limit.
    assert second[:2] == first[:2]
    router._inflight[first[0]] = 2  # saturated: spill to the other local
    third = router._pick(entry)
    assert third[2] is True and third[0] != first[0]
    # All co-located saturated -> the remote replica (not co-located).
    router._inflight[third[0]] = 2
    fourth = router._pick(entry)
    assert fourth[0] == "c" and fourth[2] is False


def test_pick_p2c_uses_pushed_depth_and_exclude():
    entry = {"max_concurrent_queries": 8,
             "replicas": [("r1", _Handle("r1")), ("r2", _Handle("r2"))],
             "nodes": {"r1": "node-b", "r2": "node-c"},
             "depths": {"r1": 6, "r2": 0}}
    router, entry = _router_with(entry, local_node="node-a")
    # Two candidates: p2c compares both every draw -> always the lighter.
    for _ in range(10):
        assert router._pick(entry)[0] == "r2"
    # Excluding the winner forces the heavier one.
    assert router._pick(entry, exclude={"r2"})[0] == "r1"
    # Saturation is respected even when excluded set empties the field.
    router._inflight["r1"] = 8
    assert router._pick(entry, exclude={"r2"}) is None


def test_pick_never_routes_outside_the_table():
    # DEAD/draining replicas are removed from the table by the
    # controller; _pick can only ever return a listed (RUNNING) replica.
    entry = {"max_concurrent_queries": 4,
             "replicas": [("live", _Handle("live"))],
             "nodes": {}, "depths": {}}
    router, entry = _router_with(entry, local_node=None)
    for _ in range(20):
        assert router._pick(entry)[0] == "live"


# ------------------------------------------------- replica frame dispatch


def _run_replica_frame(user_cls, reqs, bodies):
    from ray_tpu.serve.replica import Replica

    replica = Replica("D", user_cls, (), {}, "D#0")
    frame = b"".join(
        bytes(p) for p in dataplane.encode_frame({"v": 1, "reqs": reqs},
                                                 [b for b in bodies if b]))

    async def main():
        return await replica.__serve_raw_dispatch__(memoryview(frame))

    out = b"".join(bytes(p) for p in asyncio.run(main()))
    meta, region = dataplane.decode_frame(out)
    chunks = dataplane.slice_bodies(region,
                                    [r["n"] for r in meta["resps"]])
    return meta["resps"], [bytes(c) for c in chunks], replica


def test_coalesced_frame_isolates_per_request_errors():
    class Flaky:
        def __call__(self, payload):
            if payload == "bad":
                raise ValueError("poisoned request")
            return {"ok": payload}

    reqs = [{"k": "http", "m": "POST", "n": len(b)} for b in
            (b'"good"', b'"bad"', b'"also-good"')]
    resps, chunks, _ = _run_replica_frame(
        Flaky, reqs, [b'"good"', b'"bad"', b'"also-good"'])
    assert "err" not in resps[0] and json.loads(chunks[0]) == {
        "result": {"ok": "good"}}
    assert "poisoned request" in resps[1]["err"]
    assert resps[1].get("code") == 500
    assert "err" not in resps[2] and json.loads(chunks[2]) == {
        "result": {"ok": "also-good"}}


def test_draining_replica_refuses_frames_as_retriable():
    class Echo:
        def __call__(self, payload):
            return payload

    from ray_tpu.serve.replica import Replica

    replica = Replica("D", Echo, (), {}, "D#0")
    replica._draining = True
    frame = b"".join(bytes(p) for p in dataplane.encode_frame(
        {"v": 1, "reqs": [{"k": "http", "m": "GET", "n": 0}]}, []))

    async def main():
        return await replica.__serve_raw_dispatch__(memoryview(frame))

    meta, _ = dataplane.decode_frame(
        b"".join(bytes(p) for p in asyncio.run(main())))
    entry = meta["resps"][0]
    assert entry["retriable"] is True and "draining" in entry["err"]


def test_batched_method_gangs_one_frame():
    sizes = []

    class Batched:
        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.05)
        async def __call__(self, items):
            sizes.append(len(items))
            return [i * 2 for i in items]

    reqs = [{"k": "http", "m": "POST", "n": 1} for _ in range(4)]
    resps, chunks, _ = _run_replica_frame(
        Batched, reqs, [b"1", b"2", b"3", b"4"])
    assert [json.loads(c)["result"] for c in chunks] == [2, 4, 6, 8]
    # One coalesced frame -> ONE gang batch (single flusher wakeup).
    assert sizes == [4]


# ------------------------------------------------------------- end to end


def test_fastpath_echo_is_pickle_free(serve_cluster):
    @serve.deployment(num_replicas=2, max_concurrent_queries=32)
    class Echo:
        def __call__(self, payload):
            return payload

    serve.run(Echo.bind())
    port = serve.http_port()
    c0 = _proxy_counters()
    n = 30
    for i in range(n):
        status, body = _post(port, "/Echo", i)
        assert status == 200 and json.loads(body) == {"result": i}
    # Non-JSON payloads ride raw too: a str result returns as raw text.
    status, body = _post(port, "/Echo", b"not json at all")
    assert status == 200 and body == b"not json at all"
    c1 = _proxy_counters()
    assert c1["raw_requests"] - c0["raw_requests"] == n + 1
    assert c1["fallback_requests"] == c0["fallback_requests"]
    # Replica side saw the same requests as raw frames.
    got = 0
    for rid in ("Echo#0", "Echo#1"):
        rep = ray_tpu.get_actor(f"SERVE_REPLICA::{rid}", namespace="serve")
        got += ray_tpu.get(rep.stats.remote())["fastpath"]["requests"]
    assert got >= n + 1


def test_fastpath_bytes_response_raw(serve_cluster):
    @serve.deployment(num_replicas=1)
    class Blob:
        def __call__(self, payload):
            return b"\x00\x01binary"

    serve.run(Blob.bind())
    port = serve.http_port()
    status, body = _post(port, "/Blob", {"x": 1})
    assert status == 200 and body == b"\x00\x01binary"


def test_fastpath_generator_streams_chunks(serve_cluster):
    @serve.deployment(num_replicas=1)
    class Gen:
        def __call__(self, payload):
            def produce():
                for i in range(5):
                    yield f"tok{i} "
            return produce()

    serve.run(Gen.bind())
    port = serve.http_port()
    c0 = _proxy_counters()
    status, body = _post(port, "/Gen", {"go": 1})
    assert status == 200
    assert body == b"tok0 tok1 tok2 tok3 tok4 "
    c1 = _proxy_counters()
    assert c1["stream_pulls"] > c0["stream_pulls"]


def test_replica_death_mid_request_retries_once(serve_cluster):
    @serve.deployment(num_replicas=2, max_concurrent_queries=4)
    class Slow:
        def __call__(self, payload):
            time.sleep(0.8)
            return {"done": payload}

    serve.run(Slow.bind())
    port = serve.http_port()
    _post(port, "/Slow", -1)  # warm both the route and a connection

    with concurrent.futures.ThreadPoolExecutor(8) as pool:
        futs = [pool.submit(_post, port, "/Slow", i, 60) for i in range(8)]
        time.sleep(0.3)  # both replicas now hold in-flight requests
        victim = ray_tpu.get_actor("SERVE_REPLICA::Slow#0",
                                   namespace="serve")
        ray_tpu.kill(victim)
        results = [f.result() for f in futs]
    # Every request completed exactly once despite the mid-flight death:
    # the lost frame's requests were re-routed to the surviving replica.
    assert all(status == 200 for status, _ in results)
    assert _proxy_counters()["retries"] >= 1


def test_draining_requests_reroute_e2e(serve_cluster):
    @serve.deployment(num_replicas=2, max_concurrent_queries=16)
    class Echo:
        def __call__(self, payload):
            return payload

    serve.run(Echo.bind())
    port = serve.http_port()
    _post(port, "/Echo", 0)
    # Put one replica into draining out from under the router: the fast
    # lane must treat its refusal as retriable and re-route, so no
    # request is ever served by (or failed on) a draining replica.
    rep = ray_tpu.get_actor("SERVE_REPLICA::Echo#0", namespace="serve")
    ray_tpu.get(rep.prepare_shutdown.remote(0.1))
    for i in range(6):
        status, body = _post(port, "/Echo", i)
        assert status == 200 and json.loads(body) == {"result": i}


def test_scale_to_zero_round_trip(serve_cluster):
    @serve.deployment(
        max_concurrent_queries=8,
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=0, max_replicas=2, target_ongoing_requests=4.0,
            upscale_delay_s=0.2, downscale_delay_s=0.6),
    )
    class Cold:
        def __call__(self, payload):
            return {"served": payload}

    serve.run(Cold.bind())
    port = serve.http_port()
    # Deploys PARKED: route exists, zero replicas.
    st = serve.status()["Cold"]
    assert st["target"] == 0 and not st["replicas"]

    # First request cold-starts a replica through the wake path. The
    # bound is deliberately generous for tier-1 (the bench captures the
    # real number); correctness is "buffered, then served".
    t0 = time.monotonic()
    status, body = _post(port, "/Cold", 1, timeout=40)
    cold_ms = (time.monotonic() - t0) * 1e3
    assert status == 200 and json.loads(body) == {"result": {"served": 1}}
    assert cold_ms < 30_000
    st = serve.status()["Cold"]
    assert st["cold_start_ms"] is not None

    # Idle long enough -> parked again (scale back to zero).
    deadline = time.time() + 30
    while time.time() < deadline:
        st = serve.status()["Cold"]
        if st["target"] == 0 and not st["replicas"]:
            break
        time.sleep(0.2)
    else:
        pytest.fail("deployment did not scale back to zero when idle")

    # And the next request wakes it again.
    status, body = _post(port, "/Cold", 2, timeout=40)
    assert status == 200 and json.loads(body) == {"result": {"served": 2}}


def test_handle_path_scale_to_zero(serve_cluster):
    @serve.deployment(
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=0, max_replicas=1, upscale_delay_s=0.2,
            downscale_delay_s=5.0),
    )
    class Cold:
        def __call__(self, payload):
            return payload + 1

    handle = serve.run(Cold.bind())
    # Python handles wake parked deployments through the same router.
    assert ray_tpu.get(handle.remote(41), timeout=40) == 42


def test_park_buffer_byte_cap(monkeypatch):
    from ray_tpu.core.config import GLOBAL_CONFIG

    class _StubRouter:
        _version = 0

        def reserve_fast(self, deployment, exclude=None, model_id=None):
            return None

        def deployment_state(self, deployment):
            return "parked"

        def live_tenants(self):
            return set()

        def entry_snapshot(self, deployment):
            return None

        def wake(self, deployment):
            pass

        def has_replicas(self, deployment):
            return False

        def live_replica_ids(self):
            return set()

        def release(self, replica_id):
            pass

    monkeypatch.setattr(GLOBAL_CONFIG, "serve_park_max_bytes", 8)
    monkeypatch.setattr(GLOBAL_CONFIG, "serve_park_timeout_s", 0.2)
    lane = dataplane.FastLane(_StubRouter(), runtime=None)

    async def main():
        loop = asyncio.get_running_loop()
        with pytest.raises(dataplane.ParkBufferFull):
            await lane.dispatch(loop, "D", {"k": "http"}, b"x" * 64)
        # Under the cap the request buffers, then times out unserved.
        with pytest.raises(TimeoutError):
            await lane.dispatch(loop, "D", {"k": "http"}, b"xx")
        assert lane._park_bytes == {}  # accounting drained on both paths

    asyncio.run(main())
    assert dataplane.COUNTERS["park_rejected"] >= 1


def test_grpc_rides_the_same_fastpath(serve_cluster):
    grpc = pytest.importorskip("grpc")
    msgpack = pytest.importorskip("msgpack")

    @serve.deployment(num_replicas=1)
    class Echo:
        def __call__(self, payload):
            return {"via": payload}

    serve.run(Echo.bind())
    port = serve.grpc_port()
    ch = grpc.insecure_channel(f"127.0.0.1:{port}")
    call = ch.unary_unary("/ray_tpu.serve/Echo")
    out = msgpack.unpackb(call(msgpack.packb("grpc"), timeout=30))
    assert out == {"via": "grpc"}
    gp = ray_tpu.get_actor("SERVE_GRPC_PROXY", namespace="serve")
    counters = ray_tpu.get(gp.counters.remote())
    # Shared-path proof: the gRPC ingress dispatched through the SAME
    # raw fast lane as HTTP (raw counter moved, no pickle fallback).
    assert counters["raw_requests"] >= 1
    assert counters["fallback_requests"] == 0
    ch.close()
