"""Serve gRPC ingress (reference `serve/_private/proxy.py` gRPCProxy).

Stub-free protocol: unary bytes on `/ray_tpu.serve/<Deployment>`,
msgpack-decodable bodies decoded for the deployment callable, routed
through the same ReplicaDispatcher light lane as HTTP."""

import pytest

grpc = pytest.importorskip("grpc")
msgpack = pytest.importorskip("msgpack")

import ray_tpu
from ray_tpu import serve


@pytest.fixture()
def grpc_serve(ray_start_regular):
    @serve.deployment(num_replicas=1)
    class Echo:
        def __call__(self, payload):
            return {"echo": payload}

    @serve.deployment(route_prefix="/Boomer")
    class Boomer:
        def __call__(self, payload):
            raise RuntimeError("deliberate grpc failure")

    @serve.deployment(route_prefix="/Raw")
    class Raw:
        def __call__(self, payload):
            # Opaque-bytes passthrough: payload arrives as bytes when not
            # msgpack, and a bytes result returns verbatim.
            assert isinstance(payload, bytes)
            return payload[::-1]

    serve.run(Echo.bind())
    serve.run(Boomer.bind())
    serve.run(Raw.bind())
    port = serve.grpc_port()
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    try:
        yield channel
    finally:
        channel.close()
        serve.shutdown()


def _call(channel, deployment: str, body: bytes, timeout=30) -> bytes:
    method = channel.unary_unary(f"/ray_tpu.serve/{deployment}")
    return method(body, timeout=timeout)


def test_grpc_echo_msgpack_roundtrip(grpc_serve):
    for payload in [{"x": 1, "s": "hi"}, [1, 2, 3], 42, "text"]:
        out = msgpack.unpackb(
            _call(grpc_serve, "Echo", msgpack.packb(payload)), raw=False)
        assert out == {"echo": payload}


def test_grpc_opaque_bytes_passthrough(grpc_serve):
    # 0xc1 is never valid msgpack, so the body stays bytes end to end.
    blob = b"\xc1raw-bytes-body"
    assert _call(grpc_serve, "Raw", blob) == blob[::-1]


def test_grpc_deployment_error_is_internal(grpc_serve):
    with pytest.raises(grpc.RpcError) as err:
        _call(grpc_serve, "Boomer", msgpack.packb({}))
    assert err.value.code() == grpc.StatusCode.INTERNAL
    assert "deliberate grpc failure" in err.value.details()


def test_grpc_generator_deployment_unimplemented(grpc_serve, ray_start_regular):
    @serve.deployment(route_prefix="/Gen")
    class Gen:
        def __call__(self, payload):
            def gen():
                yield 1
            return gen()

    serve.run(Gen.bind())
    with pytest.raises(grpc.RpcError) as err:
        _call(grpc_serve, "Gen", msgpack.packb({}))
    assert err.value.code() == grpc.StatusCode.UNIMPLEMENTED
    assert "HTTP proxy" in err.value.details()


def test_grpc_unknown_deployment_not_found(grpc_serve):
    with pytest.raises(grpc.RpcError) as err:
        _call(grpc_serve, "Nope", msgpack.packb({}))
    assert err.value.code() == grpc.StatusCode.NOT_FOUND


def test_grpc_and_http_share_deployments(grpc_serve):
    import json
    import urllib.request

    http = serve.http_port()
    req = urllib.request.Request(
        f"http://127.0.0.1:{http}/Echo", data=json.dumps({"via": "http"}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert json.loads(resp.read()) == {"result": {"echo": {"via": "http"}}}
    out = msgpack.unpackb(
        _call(grpc_serve, "Echo", msgpack.packb({"via": "grpc"})), raw=False)
    assert out == {"echo": {"via": "grpc"}}
