"""Sharded replica groups (ISSUE 9): gang scheduling, tensor-parallel
serving, resharding checkpoints.

Strategy mirrors the serve suites: pure logic (ShardSpec validation,
engine tp parity, checkpoint resharding) runs in-driver on the forced
8-device CPU platform; gang lifecycle (all-or-nothing abort, rank-death
group restart with dataplane failover, scale-to-zero groups) runs end to
end on an in-process cluster where rank actors are real worker
subprocesses inheriting the multi-device env (`multi_device_workers`).
"""

import concurrent.futures
import json
import time
import urllib.request

import pytest

import ray_tpu
from conftest import assert_compiles_once
from ray_tpu import serve, shardgroup


@pytest.fixture()
def shard_cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _post(port, path, payload, timeout=60):
    data = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, resp.read()


# ------------------------------------------------------------------ spec


def test_shard_spec_validation():
    assert shardgroup.ShardSpec(tp=4, world_size=2).tp_per_rank == 2
    with pytest.raises(ValueError):
        shardgroup.ShardSpec(tp=0)
    with pytest.raises(ValueError):
        shardgroup.ShardSpec(tp=3, world_size=2)
    # A pure gang without tensor parallelism is legal (tp=1, ws=N).
    assert shardgroup.ShardSpec(tp=1, world_size=3).tp_per_rank == 1
    # Bundle derivation: explicit bundle wins, else actor options.
    spec = shardgroup.ShardSpec(tp=2, bundle={"CPU": 2})
    assert spec.rank_bundle({"num_cpus": 8}) == {"CPU": 2.0}
    assert shardgroup.ShardSpec(tp=2).rank_bundle(
        {"num_cpus": 1, "resources": {"TPU-v5e": 4}}) == \
        {"CPU": 1.0, "TPU-v5e": 4.0}


def test_llama_tp_validation():
    from ray_tpu.models.llama import LlamaConfig, validate_tp

    cfg = LlamaConfig.tiny()
    validate_tp(cfg, 2)               # 4 heads / 2 kv heads / 352 / 512
    with pytest.raises(ValueError):
        validate_tp(cfg, 8)           # kv heads (2) don't split 8 ways


def test_worker_sees_forced_devices(multi_device_workers, shard_cluster):
    """The conftest env export reaches worker subprocesses: a task in a
    worker sees the same forced device count as the driver."""

    @ray_tpu.remote
    def count_devices():
        import jax

        return len(jax.devices())

    assert ray_tpu.get(count_devices.remote(),
                       timeout=120) == multi_device_workers


# --------------------------------------------------- engine tp parity


def test_engine_tp_decode_parity_and_compile_once(multi_device_workers):
    """Satellite: sharded-vs-single-host decode parity on the CPU mesh —
    a tp=2 engine (params AND paged arena sharded) emits token-for-token
    what the single-device engine emits, with the compile-once
    discipline intact on both."""
    import jax

    from ray_tpu.inference.engine import EngineConfig, InferenceEngine
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh

    cfg = EngineConfig(model_size="tiny", max_model_len=128)
    mesh = build_mesh(MeshSpec({"tp": 2}), devices=jax.devices()[:2])
    outs = {}
    for name, engine in (("single", InferenceEngine(cfg)),
                         ("tp2", InferenceEngine(cfg, mesh=mesh))):
        reqs = [engine.add_request([1, 2, 3, 4, 5], max_new_tokens=10),
                engine.add_request([7, 8, 9], max_new_tokens=8)]
        engine.run_until_idle()
        outs[name] = [list(r.generated) for r in reqs]
        engine.check_no_leaks()
        assert_compiles_once(engine.stats(), "prefill_compiles",
                             "decode_compiles", context=name)
    assert outs["single"] == outs["tp2"]
    # The arena really is sharded on its kv-head dim.
    engine_tp = InferenceEngine(cfg, mesh=mesh)
    spec = engine_tp._arenas[0][0].sharding.spec
    assert tuple(spec) == (None, None, "tp")


# ------------------------------------------------ resharding checkpoints


def test_resharding_roundtrip_bit_exact(multi_device_workers, tmp_path):
    """Satellite: tp=2 save -> tp=1 and tp=4 restore, bit-for-bit."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.llama import (
        Llama,
        LlamaConfig,
        shard_params_tp,
        tp_shardings,
    )
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.train.checkpoint import (
        Checkpoint,
        restore_sharded_pytree,
        save_sharded_pytree,
        sharded_manifest,
    )

    model = Llama(LlamaConfig.tiny(seq=64))
    params = jax.jit(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)))()
    mesh2 = build_mesh(MeshSpec({"tp": 2}), devices=jax.devices()[:2])
    mesh4 = build_mesh(MeshSpec({"tp": 4}), devices=jax.devices()[:4])
    params_tp2 = shard_params_tp(model, params, mesh2)

    path = str(tmp_path / "ckpt")
    save_sharded_pytree(path, params_tp2, meta={"tp": 2})
    assert sharded_manifest(path)["meta"]["tp"] == 2

    target = jax.eval_shape(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)))
    base = [np.asarray(x) for x in jax.tree.leaves(params)]

    restored_host = restore_sharded_pytree(path, target=target)
    restored_tp4 = restore_sharded_pytree(
        path, target=target, shardings=tp_shardings(model, mesh4))
    for restored in (restored_host, restored_tp4):
        got = [np.asarray(x) for x in jax.tree.leaves(restored)]
        assert len(got) == len(base)
        for a, b in zip(base, got):
            assert a.dtype == b.dtype and np.array_equal(a, b)

    # Functional check: resharded params drive the model to the same
    # logits the original params produce (bf16 partial-sum order differs
    # across shardings, so this is close-to, not bitwise — bitwise is
    # asserted on the PARAMS above, and greedy-decode parity end to end
    # in test_engine_tp_decode_parity_and_compile_once).
    ids = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    ref = np.asarray(model.apply(params, ids), np.float32)
    out = np.asarray(model.apply(restored_tp4, ids), np.float32)
    np.testing.assert_allclose(out, ref, atol=0.02, rtol=0)

    # Checkpoint-object front door.
    ck = Checkpoint.from_sharded_pytree(params_tp2,
                                        path=str(tmp_path / "ck2"))
    again = ck.get_sharded_pytree(target=target)
    for a, b in zip(base, [np.asarray(x) for x in jax.tree.leaves(again)]):
        assert np.array_equal(a, b)


def test_sharded_manifest_detects_missing_rank(tmp_path):
    """A merge over an incomplete rank set (a rank never saved) fails
    the coverage check instead of silently restoring garbage."""
    import json as _json
    import os

    from ray_tpu.train.checkpoint import merge_sharded_manifest

    path = str(tmp_path)
    with open(os.path.join(path, "manifest.p0.json"), "w") as f:
        _json.dump({"process_index": 0, "process_count": 2, "meta": {},
                    "entries": {"w": {"shape": [4, 4], "dtype": "float32",
                                      "shards": [{"file": "w.bin",
                                                  "index": [[0, 2],
                                                            [0, 4]]}]}}},
                   f)
    with open(os.path.join(path, "manifest.p1.json"), "w") as f:
        _json.dump({"process_index": 1, "process_count": 2, "meta": {},
                    "entries": {"w": {"shape": [4, 4], "dtype": "float32",
                                      "shards": []}}}, f)
    with pytest.raises(ValueError, match="covers only"):
        merge_sharded_manifest(path, process_count=2)


# --------------------------------------------------------- gang creation


class _FailingRank:
    """Deployment whose rank 2 explodes in its ctor."""

    def __init__(self):
        ctx = shardgroup.current()
        if ctx is not None and ctx.rank == 2:
            raise RuntimeError("rank 2 exploded in ctor")

    def __call__(self, payload):
        return payload


def test_gang_all_or_nothing_abort(shard_cluster):
    """Satellite: a mid-gang ctor failure aborts the WHOLE gang — one
    rank-attributed error, every bundle released, no half-alive ranks."""
    before = ray_tpu.available_resources().get("CPU", 0)
    with pytest.raises(shardgroup.GangError) as err:
        shardgroup.create_replica_group(
            _FailingRank, shardgroup.ShardSpec(tp=1, world_size=4),
            deployment_name="failgang", actor_options={"num_cpus": 0.5},
            ready_timeout_s=60)
    assert err.value.rank == 2
    assert "rank 2" in str(err.value)
    group_id = err.value.group_id
    # Every bundle released (the pg is gone, reservations returned).
    deadline = time.time() + 10
    while time.time() < deadline:
        if abs(ray_tpu.available_resources().get("CPU", 0) - before) < 0.01:
            break
        time.sleep(0.1)
    assert abs(ray_tpu.available_resources().get("CPU", 0) - before) < 0.01
    # No half-alive ranks: every rank actor of the gang is gone.
    for rank in range(4):
        with pytest.raises(Exception):
            ray_tpu.get_actor(f"SHARDGROUP::{group_id}#r{rank}")


def test_gang_bundle_overflow_fails_fast(shard_cluster):
    """A rank asking for more than its bundle is a GangError in
    milliseconds, not an unplaceable creation spinning for minutes."""
    t0 = time.time()
    with pytest.raises(shardgroup.GangError, match="bundle"):
        shardgroup.create_gang(
            _FailingRank, shardgroup.ShardSpec(tp=1, world_size=2,
                                               bundle={"CPU": 0.1}),
            rank_options=lambda r: {"num_cpus": 2.0})
    assert time.time() - t0 < 5.0


def test_gang_infeasible_pg_released(shard_cluster):
    before = ray_tpu.available_resources().get("CPU", 0)
    with pytest.raises(shardgroup.GangError, match="not placeable"):
        shardgroup.create_replica_group(
            _FailingRank,
            shardgroup.ShardSpec(tp=1, world_size=3, bundle={"CPU": 64}),
            deployment_name="toolarge", pg_timeout_s=2)
    time.sleep(0.5)
    assert abs(ray_tpu.available_resources().get("CPU", 0) - before) < 0.01


def test_gang_monitor_fires_once_on_rank_death(shard_cluster):
    class Idle:
        def __call__(self, payload):
            return payload

    deaths = []
    group = shardgroup.create_replica_group(
        Idle, shardgroup.ShardSpec(tp=1, world_size=2),
        deployment_name="mon",
        on_death=lambda g, rank: deaths.append(rank))
    assert group.check_alive(timeout_s=10)
    ray_tpu.kill(group.ranks[1])
    deadline = time.time() + 15
    while not deaths and time.time() < deadline:
        time.sleep(0.1)
    assert deaths == [1]
    group.kill()


# ------------------------------------------------- serve: sharded llama


@pytest.mark.parametrize("prompt", [[1, 2, 3, 4, 5]])
def test_sharded_llama_http_parity(multi_device_workers, shard_cluster,
                                   prompt):
    """Acceptance: a tp=2 sharded llama gang serves token-for-token the
    SAME ids as the single-device deployment through the serve HTTP
    path (same seed -> same weights; the mesh is the only difference)."""
    from ray_tpu.inference.api import LLMServer

    plain = LLMServer.options(name="LLMPlain")
    sharded = LLMServer.options(
        name="LLMShard", shard_spec=serve.ShardSpec(tp=2, world_size=1))
    serve.run(plain.bind("tiny", 128, 8), timeout_s=180)
    serve.run(sharded.bind("tiny", 128, 8), timeout_s=180)
    port = serve.http_port()
    payload = {"ids": prompt, "max_new_tokens": 8}
    status_p, body_p = _post(port, "/LLMPlain", payload, timeout=120)
    status_s, body_s = _post(port, "/LLMShard", payload, timeout=120)
    assert status_p == 200 and status_s == 200
    ids_plain = json.loads(body_p)["result"]["ids"]
    ids_sharded = json.loads(body_s)["result"]["ids"]
    assert ids_plain[:len(prompt)] == prompt
    assert ids_sharded == ids_plain
    # The sharded replica really ran as a gang rank with an active
    # shard context (not a silent single-device fallback).
    rep = ray_tpu.get_actor("SERVE_REPLICA::LLMShard#0", namespace="serve")
    stats = ray_tpu.get(rep.stats.remote(), timeout=30)
    assert stats["shard"]["tp"] == 2
    assert stats["user"]["queue_depth"] == 0


# ------------------------------------- serve: rank death -> group restart


def test_rank_death_failover_and_group_restart(shard_cluster):
    """Acceptance: killing one rank of a serving group never hangs a
    request — in-flight requests fail over per the dataplane retry-once
    contract, and the group restarts as a unit within a bounded time."""

    @serve.deployment(num_replicas=2, max_concurrent_queries=8,
                      shard_spec=serve.ShardSpec(tp=1, world_size=2))
    class Slow:
        def __call__(self, payload):
            time.sleep(0.4)
            return {"done": payload}

    serve.run(Slow.bind(), timeout_s=120)
    port = serve.http_port()
    _post(port, "/Slow", -1)  # warm route + connection

    with concurrent.futures.ThreadPoolExecutor(8) as pool:
        futs = [pool.submit(_post, port, "/Slow", i, 60) for i in range(16)]
        time.sleep(0.3)
        # Kill a NON-routed rank: the router never saw it, but its death
        # must still take the whole group down (and back up).
        victim = ray_tpu.get_actor("SERVE_RANK::Slow#0#r1",
                                   namespace="serve")
        ray_tpu.kill(victim)
        killed_at = time.time()
        results = [f.result() for f in futs]
    # Every request completed exactly once; none hung, none failed.
    assert all(status == 200 for status, _ in results)
    # The group restarts AS A UNIT within a bounded time: a replacement
    # replica id reaches RUNNING and the old gang is fully gone.
    deadline = killed_at + 25
    new_running = None
    while time.time() < deadline:
        replicas = serve.status().get("Slow", {}).get("replicas", {})
        fresh = [rid for rid, state in replicas.items()
                 if rid not in ("Slow#0",) and state == "RUNNING"]
        if len(fresh) >= 2 and "Slow#0" not in replicas:
            new_running = fresh
            break
        time.sleep(0.2)
    assert new_running is not None, serve.status()
    for name in ("SERVE_REPLICA::Slow#0", "SERVE_RANK::Slow#0#r1"):
        with pytest.raises(Exception):
            ray_tpu.get_actor(name, namespace="serve")
    # The restarted group serves.
    status, body = _post(port, "/Slow", 99)
    assert status == 200 and json.loads(body) == {"result": {"done": 99}}


def test_group_scale_to_zero_cold_start(shard_cluster):
    """Scale-to-zero operates on WHOLE groups: a parked gang deployment
    cold-starts all ranks on first arrival and answers from rank 0."""

    @serve.deployment(
        max_concurrent_queries=8,
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=0, max_replicas=1, downscale_delay_s=60.0),
        shard_spec=serve.ShardSpec(tp=1, world_size=2))
    class Cold:
        def __call__(self, payload):
            return {"woke": payload}

    serve.run(Cold.bind(), timeout_s=120)
    assert serve.status()["Cold"]["replicas"] == {}  # deployed parked
    port = serve.http_port()
    status, body = _post(port, "/Cold", 7, timeout=60)
    assert status == 200 and json.loads(body) == {"result": {"woke": 7}}
    replicas = serve.status()["Cold"]["replicas"]
    assert list(replicas.values()) == ["RUNNING"]
    rid = next(iter(replicas))
    # Both ranks of the woken gang exist.
    ray_tpu.get_actor(f"SERVE_REPLICA::{rid}", namespace="serve")
    ray_tpu.get_actor(f"SERVE_RANK::{rid}#r1", namespace="serve")
