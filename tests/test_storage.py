"""URI storage backends + cloud-capable checkpoints + Tune sync.

Reference: `python/ray/air/checkpoint.py:65` (dict<->dir<->URI morphs over
cloud storage), `python/ray/tune/syncer.py` (experiment sync). Cloud
schemes are exercised against the in-memory backend and against fake
transports that verify the exact REST requests.
"""

import json
import os

import pytest

from ray_tpu.train import storage
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.storage import GCSBackend, MemoryBackend, S3Backend


@pytest.fixture(autouse=True)
def _clean_memory():
    MemoryBackend.clear()
    yield
    MemoryBackend.clear()
    storage.set_transport("gs", None)
    storage.set_transport("s3", None)


def test_parse_uri():
    assert storage.parse_uri("gs://bkt/a/b") == ("gs", "bkt", "a/b")
    assert storage.parse_uri("file:///tmp/x") == ("file", "", "/tmp/x")
    with pytest.raises(ValueError):
        storage.parse_uri("/plain/path")


def test_memory_backend_roundtrip(tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "a.txt").write_text("alpha")
    (src / "sub" / "b.txt").write_text("beta")

    storage.upload_dir(str(src), "memory://bkt/exp1")
    assert storage.uri_exists("memory://bkt/exp1")
    dest = tmp_path / "dest"
    storage.download_dir("memory://bkt/exp1", str(dest))
    assert (dest / "a.txt").read_text() == "alpha"
    assert (dest / "sub" / "b.txt").read_text() == "beta"

    storage.delete_prefix("memory://bkt/exp1")
    assert not storage.uri_exists("memory://bkt/exp1")


def test_checkpoint_uri_roundtrip_through_cloud():
    ckpt = Checkpoint.from_dict({"step": 7, "w": [1, 2, 3]})
    uri = ckpt.to_uri("memory://ckpts/run1/chk0")
    back = Checkpoint.from_uri(uri)
    assert back.to_dict() == {"step": 7, "w": [1, 2, 3]}


def test_gcs_backend_requests():
    calls = []

    def fake(method, url, data=None, headers=None):
        calls.append((method, url, data, headers))
        if "metadata.google.internal" in url:
            return json.dumps({"access_token": "tok",
                               "expires_in": 3600}).encode()
        if method == "GET" and "?prefix=" in url:
            return json.dumps({"items": [{"name": "p/x.bin"}]}).encode()
        return b"DATA" if method == "GET" else b"{}"

    storage.set_transport("gs", fake)
    backend, path = storage.get_backend("gs://my-bucket/p")
    assert isinstance(backend, GCSBackend) and path == "p"
    backend.put("p/x.bin", b"hello")
    put = next(c for c in calls if c[0] == "POST")
    assert put[1] == ("https://storage.googleapis.com/upload/storage/v1/b/"
                      "my-bucket/o?uploadType=media&name=p%2Fx.bin")
    assert put[2] == b"hello"
    assert put[3]["Authorization"] == "Bearer tok"

    assert backend.list("p") == ["p/x.bin"]
    assert backend.get("p/x.bin") == b"DATA"
    get = calls[-1]
    assert get[1].endswith("/b/my-bucket/o/p%2Fx.bin?alt=media")
    backend.delete("p/x.bin")
    assert calls[-1][0] == "DELETE"


def test_s3_backend_signs_requests(monkeypatch):
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AKID")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "SECRET")
    monkeypatch.setenv("AWS_REGION", "eu-west-1")
    calls = []

    def fake(method, url, data=None, headers=None):
        calls.append((method, url, data, headers))
        if method == "GET" and "list-type=2" in url:
            return b"<r><Key>k/a</Key><Key>k/b</Key></r>"
        return b"PAYLOAD" if method == "GET" else b""

    storage.set_transport("s3", fake)
    backend, _ = storage.get_backend("s3://bkt/k")
    assert isinstance(backend, S3Backend)
    backend.put("k/a", b"v")
    method, url, data, headers = calls[-1]
    assert method == "PUT" and url.endswith("/k/a") and data == b"v"
    auth = headers["Authorization"]
    assert auth.startswith("AWS4-HMAC-SHA256 Credential=AKID/")
    assert "/eu-west-1/s3/aws4_request" in auth
    assert "Signature=" in auth
    assert headers["x-amz-content-sha256"] == \
        __import__("hashlib").sha256(b"v").hexdigest()
    assert backend.list("k") == ["k/a", "k/b"]


def test_unknown_scheme_raises():
    with pytest.raises(ValueError, match="no storage backend"):
        storage.get_backend("azure://x/y")


def test_tune_cloud_sync_and_restore(tmp_path):
    """Tuner with a cloud storage_path syncs the experiment to the bucket
    and Tuner.restore() resumes from the URI (reference tune/syncer.py +
    Tuner.restore from cloud)."""
    import shutil

    import ray_tpu
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    try:
        def ckpt_trainable(config):
            start = 0
            ckpt = tune.get_checkpoint()
            if ckpt is not None:
                start = ckpt.to_dict()["step"] + 1
            for step in range(start, 3):
                tune.report({"loss": 1.0 / (step + 1), "step": step},
                            checkpoint=Checkpoint.from_dict({"step": step}))

        uri = "memory://tunebkt/exp_sync"
        run = RunConfig(name="exp_sync", storage_path="memory://tunebkt")
        tuner = tune.Tuner(ckpt_trainable,
                           param_space={"x": tune.grid_search([1, 2])},
                           tune_config=tune.TuneConfig(metric="loss",
                                                       mode="min"),
                           run_config=run)
        results = tuner.fit()
        assert not results.errors
        # Bucket holds the experiment state + trial checkpoints.
        assert storage.uri_exists(uri + "/tuner.pkl")
        names = MemoryBackend("tunebkt").list("exp_sync")
        assert any("checkpoint_" in n for n in names), names
        assert tune.Tuner.can_restore(uri)

        # Simulate losing the local working dir (VM death), then restore
        # from the bucket alone.
        local = os.path.join(os.path.expanduser("~"),
                             ".cache", "ray_tpu", "tune_sync", "exp_sync")
        shutil.rmtree(local, ignore_errors=True)
        restored = tune.Tuner.restore(uri, ckpt_trainable)
        results2 = restored.fit()
        assert len(results2) == 2 and not results2.errors
        for r in results2:
            assert r.checkpoint is not None
            assert r.checkpoint.to_dict()["step"] == 2
    finally:
        ray_tpu.shutdown()
