"""Multi-tenancy plane (ISSUE 11): registry, admission, WFQ, restore.

Strategy mirrors the serve suites: the quota/fair-queue math is unit-
tested deterministically (no cluster), the enforcement path is proven
end to end over HTTP on an in-process cluster (429 + Retry-After from
the proxy door), and the controller's sharded reconciler is proven on
checkpoint->crash->restore with a mostly-parked zoo (bounded restore,
zero replica churn, quotas preserved).
"""

import asyncio
import json
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.tenancy import (
    QuotaExceeded,
    TenantSpec,
    TokenBucket,
    WfqScheduler,
)


@pytest.fixture()
def serve_cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _post(port, path, payload, timeout=30):
    data = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, resp.read(), dict(resp.headers)


# ---------------------------------------------------------------- registry


def test_tenant_spec_tier_defaults():
    gold = TenantSpec(name="acme", tier="gold")
    assert gold.weight == 8
    bronze = TenantSpec(name="smol", tier="bronze", rps_limit=10)
    assert bronze.weight == 1
    assert bronze.burst == 10.0          # defaults to 1s of rps
    override = TenantSpec(name="w", tier="bronze", weight=3)
    assert override.weight == 3
    with pytest.raises(ValueError):
        TenantSpec(name="x", tier="platinum")
    with pytest.raises(ValueError):
        TenantSpec(name="")
    # Wire round trip (the routing table pushes qos dicts).
    assert TenantSpec(**gold.qos()) == gold


# ------------------------------------------------------------ token bucket


def test_token_bucket_admits_burst_then_meters():
    b = TokenBucket(rate=10.0, burst=5.0, now=0.0)
    assert all(b.take(now=0.0) == 0.0 for _ in range(5))
    wait = b.take(now=0.0)
    assert wait == pytest.approx(0.1)    # 1 token / 10 rps
    # Refill: 0.25s later there are 2.5 tokens.
    assert b.take(now=0.25) == 0.0
    assert b.take(now=0.25) == 0.0
    assert b.take(now=0.25) > 0.0
    # Never banks beyond burst.
    assert b.take(now=100.0) == 0.0
    b2 = TokenBucket(rate=0.0, burst=1.0, now=0.0)
    b2.take(now=0.0)
    assert b2.take(now=0.0) == float("inf")


def test_admission_bucket_survives_unrelated_republish():
    """A re-pushed entry with the SAME qos_version (depth moves, other
    tenants registering) must not rebuild the token bucket — a rebuild
    hands the tenant a full burst of fresh tokens. Only a bumped
    per-tenant version (a real spec update) rebuilds."""
    from ray_tpu.tenancy.admission import TenantAdmission

    adm = TenantAdmission()
    entry = {"qos": TenantSpec(name="t", rps_limit=2, burst=2).qos(),
             "qos_version": 5}
    st = adm.resolve(entry)
    for _ in range(2):
        adm.admit(st)
        adm.release(st)
    with pytest.raises(QuotaExceeded):
        adm.admit(adm.resolve(entry))
    # Same version re-pushed (fresh dict, as a table push delivers it):
    # the drained bucket stays drained.
    with pytest.raises(QuotaExceeded):
        adm.admit(adm.resolve(
            {"qos": dict(entry["qos"]), "qos_version": 5}))
    # A true update (bumped per-tenant version) rebuilds.
    adm.admit(adm.resolve(
        {"qos": dict(entry["qos"]), "qos_version": 6}))


# -------------------------------------------------------------------- WFQ


def test_wfq_drains_by_weight_without_starvation():
    """16 gold (weight 8) + 16 bronze (weight 1) waiters contend for a
    trickle of capacity: the first 9 admissions split ~8:1 by weight,
    and full capacity drains EVERY waiter (no starvation)."""

    async def main():
        loop = asyncio.get_running_loop()
        wfq = WfqScheduler()
        capacity = {"slots": 0}
        served = []

        def make_try(tag):
            def try_reserve():
                if capacity["slots"] > 0:
                    capacity["slots"] -= 1
                    served.append(tag)
                    return (tag, None, False)
                return None
            return try_reserve

        tasks = []
        for _ in range(16):
            tasks.append(asyncio.ensure_future(wfq.acquire(
                loop, "gold", 8, make_try("g"), 5.0)))
            tasks.append(asyncio.ensure_future(wfq.acquire(
                loop, "bronze", 1, make_try("b"), 5.0)))
        await asyncio.sleep(0.02)        # everyone parked
        assert wfq.queued() == 32
        capacity["slots"] = 9
        while len(served) < 9:
            await asyncio.sleep(0.005)
        first = served[:9]
        assert first.count("g") == 8 and first.count("b") == 1, first
        capacity["slots"] = 10_000
        await asyncio.gather(*tasks)
        assert len(served) == 32         # nobody starved
        await asyncio.sleep(0.01)        # pump exits; state resets
        assert not wfq.has_waiters()

    asyncio.run(main())


def test_wfq_timeout_and_head_of_line():
    """A waiter whose deployment never frees times out; a different
    tenant's head targeting a deployment WITH capacity is not blocked
    behind it (no cross-deployment head-of-line blocking)."""

    async def main():
        loop = asyncio.get_running_loop()
        wfq = WfqScheduler()
        stuck = asyncio.ensure_future(wfq.acquire(
            loop, "stuck", 8, lambda: None, 0.15, deployment="A"))
        ok = asyncio.ensure_future(wfq.acquire(
            loop, "other", 1, lambda: ("r", None, False), 5.0,
            deployment="B"))
        assert await ok == ("r", None, False)
        with pytest.raises(TimeoutError):
            await stuck

    asyncio.run(main())


def test_wfq_same_tenant_no_cross_deployment_blocking():
    """Queues key by (tenant, deployment): the SAME tenant's waiter for
    a deployment with free capacity is never stuck behind its earlier
    waiter for a saturated one (and neither is the untenanted pool)."""

    async def main():
        loop = asyncio.get_running_loop()
        wfq = WfqScheduler()
        blocked = asyncio.ensure_future(wfq.acquire(
            loop, None, 1, lambda: None, 0.3, deployment="sat"))
        await asyncio.sleep(0.01)   # "sat" waiter queued first
        ok = asyncio.ensure_future(wfq.acquire(
            loop, None, 1, lambda: ("r", None, False), 5.0,
            deployment="free"))
        assert await ok == ("r", None, False)
        with pytest.raises(TimeoutError):
            await blocked

    asyncio.run(main())


def test_wfq_cancelled_waiter_returns_its_grant():
    """A grant racing the waiter's cancellation (client disconnect)
    carries an already-reserved router slot: it must be handed to
    on_drop, never silently discarded (that slot would leak forever)."""

    async def main():
        loop = asyncio.get_running_loop()
        wfq = WfqScheduler()
        dropped = []
        task = asyncio.ensure_future(wfq.acquire(
            loop, None, 1, lambda: None, 5.0, deployment="d",
            on_drop=dropped.append))
        await asyncio.sleep(0.01)
        # The pump grants in the same tick the client disconnects.
        wfq._queues[("", "d")][0].fut.set_result(("slot", None, False))
        task.cancel()
        try:
            result = await task
            # py < 3.12: wait_for returns the completed result despite
            # the cancel — the grant is consumed normally, no drop.
            assert result == ("slot", None, False)
            assert dropped == []
        except asyncio.CancelledError:
            # py >= 3.12: the cancellation wins; the grant (and its
            # reserved slot) must be handed back, never discarded.
            assert dropped == [("slot", None, False)]

    asyncio.run(main())


def test_wfq_idle_deployment_bypasses_other_pools_backlog():
    """has_waiters_for: a backlog on one deployment must not force an
    idle deployment's requests through the pump (and its backoff)."""

    async def main():
        loop = asyncio.get_running_loop()
        wfq = WfqScheduler()
        blocked = asyncio.ensure_future(wfq.acquire(
            loop, None, 1, lambda: None, 0.3, deployment="sat"))
        await asyncio.sleep(0.01)
        assert wfq.has_waiters()
        assert wfq.has_waiters_for("sat")
        assert not wfq.has_waiters_for("idle")   # the dispatch bypass
        with pytest.raises(TimeoutError):
            await blocked

    asyncio.run(main())


def test_wfq_waiter_exits_on_deployment_state_change():
    """A fair-queued waiter whose deployment is deleted (or parked)
    mid-wait leaves the queue immediately and falls back through the
    dispatch loop's state handling — never polls a dead closure to the
    60s request timeout."""
    from ray_tpu.serve import dataplane

    class _Router:
        _version = 0

        def __init__(self):
            self.state = "active"

        def reserve_fast(self, d, exclude=None, model_id=None):
            return None          # always saturated

        def deployment_state(self, d):
            return self.state

        def entry_snapshot(self, d):
            return {"max_concurrent_queries": 1, "replicas": [("r", None)]}

        def live_tenants(self):
            return set()

        def live_replica_ids(self):
            return set()

        def release(self, rid):
            pass

    async def main():
        loop = asyncio.get_running_loop()
        router = _Router()
        lane = dataplane.FastLane(router, runtime=None)
        task = asyncio.ensure_future(
            lane.dispatch(loop, "D", {"k": "http"}, b"x"))
        await asyncio.sleep(0.05)            # parked in the fair queue
        assert lane._wfq.has_waiters()
        router.state = "unknown"             # deployment deleted
        t0 = time.monotonic()
        assert await task is None            # classic lane owns it now
        assert time.monotonic() - t0 < 2.0   # not the 60s timeout

    asyncio.run(main())


# ------------------------------------------------------------- e2e quotas


def test_over_quota_answers_429_with_retry_after(serve_cluster):
    serve.register_tenant("smol", tier="bronze", rps_limit=5, burst=5)

    @serve.deployment(num_replicas=1, max_concurrent_queries=8,
                      tenant="smol")
    class Echo:
        def __call__(self, payload):
            return payload

    serve.run(Echo.bind())
    port = serve.http_port()
    statuses, retry_after = [], None
    for i in range(30):
        try:
            status, _, _ = _post(port, "/Echo", {"i": i})
        except urllib.error.HTTPError as e:
            status = e.code
            if status == 429:
                retry_after = e.headers.get("Retry-After")
                body = json.loads(e.read())
                assert "quota" in body["error"]
        statuses.append(status)
    assert statuses.count(200) >= 5          # the burst was admitted
    assert statuses.count(429) >= 10         # the blast was rejected
    assert retry_after is not None and float(retry_after) > 0
    # Over-quota rejections never reached a replica (fast 429 at the
    # proxy door): the engine-side processed count equals the 200s.
    proxy = ray_tpu.get_actor("SERVE_PROXY", namespace="serve")
    counters = ray_tpu.get(proxy.counters.remote(), timeout=10)
    assert counters["quota_rejected"] >= 10


def test_unmetered_tenant_unaffected_by_neighbour_quota(serve_cluster):
    serve.register_tenant("noisy", tier="bronze", rps_limit=2, burst=2)
    serve.register_tenant("calm", tier="gold")

    @serve.deployment(num_replicas=1, tenant="noisy")
    class Noisy:
        def __call__(self, payload):
            return payload

    @serve.deployment(num_replicas=1, tenant="calm")
    class Calm:
        def __call__(self, payload):
            return payload

    serve.run(Noisy.bind())
    serve.run(Calm.bind())
    port = serve.http_port()
    noisy_429 = 0
    for i in range(10):
        try:
            _post(port, "/Noisy", {"i": i})
        except urllib.error.HTTPError as e:
            assert e.code == 429
            noisy_429 += 1
        status, _, _ = _post(port, "/Calm", {"i": i})
        assert status == 200                 # calm tenant never throttled
    assert noisy_429 >= 5


def test_deploy_with_unknown_tenant_fails_fast(serve_cluster):
    @serve.deployment(tenant="ghost")
    class Echo:
        def __call__(self, payload):
            return payload

    with pytest.raises(Exception, match="unregistered tenant"):
        serve.run(Echo.bind())


def test_tenant_registry_roundtrip_and_unregister(serve_cluster):
    serve.register_tenant("acme", tier="gold", rps_limit=100,
                          max_inflight=32)
    specs = serve.tenants()
    assert specs["acme"]["tier"] == "gold"
    assert specs["acme"]["weight"] == 8

    @serve.deployment(tenant="acme")
    class Echo:
        def __call__(self, payload):
            return payload

    serve.run(Echo.bind())
    with pytest.raises(Exception, match="still owns"):
        serve.unregister_tenant("acme")
    serve.delete("Echo")
    serve.unregister_tenant("acme")
    assert "acme" not in serve.tenants()


# ------------------------------------------- sharded reconciler + restore


def _deploy_zoo(n, tenants=("gold-t", "silver-t", "bronze-t")):
    @serve.deployment
    class ZooEcho:
        def __call__(self, payload):
            return payload

    for i, tier in enumerate(("gold", "silver", "bronze")):
        serve.register_tenant(tenants[i], tier=tier, rps_limit=500)
    for i in range(n):
        serve.run(ZooEcho.options(
            name=f"zoo{i:03d}", tenant=tenants[i % len(tenants)],
            autoscaling_config=serve.AutoscalingConfig(
                min_replicas=0, max_replicas=1)).bind())


def _controller_stats():
    from ray_tpu.serve.controller import CONTROLLER_NAME, SERVE_NAMESPACE

    c = ray_tpu.get_actor(CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
    return ray_tpu.get(c.reconcile_stats.remote(), timeout=10)


def test_reconciler_skips_parked_deployments(serve_cluster):
    """With a mostly-parked zoo the per-tick scan set stays near the
    anti-entropy shard size — NOT the deployment count."""
    _deploy_zoo(24)

    @serve.deployment(num_replicas=1)
    class Live:
        def __call__(self, payload):
            return payload

    serve.run(Live.bind())
    deadline = time.time() + 10
    while time.time() < deadline:
        stats = _controller_stats()
        if stats["ticks"] > 5 and stats["last_scanned"] <= 8:
            break
        time.sleep(0.2)
    assert stats["deployments"] == 25
    # 24 parked + 1 active: a tick scans the active deployment plus
    # ceil(24/16) = 2 anti-entropy picks, never the whole zoo.
    assert stats["last_scanned"] <= 8, stats
    assert stats["last_parked_skipped"] >= 16, stats
    # Parked deployments still wake: first request cold-starts.
    port = serve.http_port()
    status, body, _ = _post(port, "/zoo003", {"x": 1}, timeout=60)
    assert status == 200 and json.loads(body) == {"result": {"x": 1}}


@pytest.mark.slow
def test_restore_200_parked_bounded_zero_churn(serve_cluster):
    """Satellite: controller checkpoint->crash->restore with a 200-
    deployment mostly-parked zoo — restore is bounded, produces ZERO
    replica churn (no spurious kills or creates), and preserves tenant
    quotas."""
    from ray_tpu.serve.controller import CONTROLLER_NAME, SERVE_NAMESPACE

    _deploy_zoo(200)

    @serve.deployment(num_replicas=2)
    class Live:
        def __call__(self, payload):
            return payload

    serve.run(Live.bind())
    before = serve.status()
    live_before = sorted(before["Live"]["replicas"])
    assert len(live_before) == 2
    tenants_before = serve.tenants()
    assert len(tenants_before) == 3

    controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                   namespace=SERVE_NAMESPACE)
    ray_tpu.kill(controller)
    time.sleep(0.5)

    t0 = time.perf_counter()
    after = serve.status()      # transparently recreates + restores
    restore_s = time.perf_counter() - t0
    assert restore_s < 10.0, f"restore took {restore_s:.1f}s"
    assert len(after) == 201

    # Zero churn: the SAME replica ids re-adopted, no creates (a fresh
    # replica would get a new #seq suffix), parked stays parked.
    deadline = time.time() + 20
    while time.time() < deadline:
        st = serve.status()["Live"]
        if sorted(st["replicas"]) == live_before and \
                all(v == "RUNNING" for v in st["replicas"].values()):
            break
        time.sleep(0.25)
    st = serve.status()
    assert sorted(st["Live"]["replicas"]) == live_before
    parked = [n for n, d in st.items()
              if n.startswith("zoo") and not d["replicas"]]
    assert len(parked) == 200
    assert serve.tenants() == tenants_before

    # The restored reconciler settles back to a sublinear scan set.
    deadline = time.time() + 10
    while time.time() < deadline:
        stats = _controller_stats()
        if stats["ticks"] > 20 and stats["last_scanned"] <= 20:
            break
        time.sleep(0.2)
    assert stats["last_scanned"] <= 20, stats
    # And the zoo still works end to end post-restore.
    port = serve.http_port()
    status, _, _ = _post(port, "/zoo117", {"x": 1}, timeout=60)
    assert status == 200
