"""Tracing plane tests (ray_tpu/observability/).

Covers the PR-7 acceptance surface: context propagation across task /
actor / serve-HTTP / collective boundaries (one trace_id end to end),
flight-recorder boundedness under span storms, the allocate-nothing
contract for sampled-out requests, Chrome trace-event export validity
(parent/child edges reconstructible), the GCS trace store window/limit
caps, and the metrics satellites (stale-reporter expiry, registry
re-register keeping accumulated series).
"""

import time

import numpy as np
import pytest


def _tracing():
    from ray_tpu.observability import tracing

    return tracing


def _enable_local(monkeypatch=None, rate=1.0, cap=4096):
    """Enable tracing for this process only (no cluster)."""
    from ray_tpu.core.config import GLOBAL_CONFIG

    tracing = _tracing()
    GLOBAL_CONFIG._overrides["tracing_enabled"] = True
    GLOBAL_CONFIG._overrides["trace_sample_rate"] = rate
    GLOBAL_CONFIG._overrides["trace_buffer_spans"] = cap
    tracing.refresh_from_config()
    tracing.RECORDER.drain()


def _disable_local():
    from ray_tpu.core.config import GLOBAL_CONFIG

    tracing = _tracing()
    for k in ("tracing_enabled", "trace_sample_rate", "trace_buffer_spans"):
        GLOBAL_CONFIG._overrides.pop(k, None)
    tracing.refresh_from_config()
    tracing.RECORDER.drain()


@pytest.fixture()
def local_tracing():
    _enable_local()
    yield _tracing()
    _disable_local()


# --------------------------------------------------------------------- #
# Tracer unit behavior
# --------------------------------------------------------------------- #


def test_disabled_path_is_shared_noop_singleton():
    tracing = _tracing()
    _disable_local()
    spans = [tracing.get_tracer().start_span(f"s{i}") for i in range(10)]
    assert all(s is tracing.NOOP_SPAN for s in spans)
    assert len(tracing.RECORDER) == 0


def test_sampled_out_requests_allocate_nothing():
    """With the sample rate at 0, every start_span returns the SAME
    no-op object and the recorder never grows — the sampled-out path
    provably allocates no span state."""
    tracing = _tracing()
    _enable_local(rate=0.0)
    try:
        for _ in range(100):
            span = tracing.get_tracer().start_span("req")
            assert span is tracing.NOOP_SPAN
            span.end()
        assert len(tracing.RECORDER) == 0
        # Spec contexts are minted (tasks need ids regardless) but marked
        # unsampled, so remote sides do not re-roll the decision.
        ctx = tracing.child_spec_ctx()
        assert ctx["sampled"] is False
    finally:
        _disable_local()


def test_span_nesting_and_context_restore(local_tracing):
    tracing = local_tracing
    tracer = tracing.get_tracer()
    with tracer.start_span("root") as root:
        assert tracing.capture()["span_id"] == root.span_id
        with tracer.start_span("child") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
        # inner end restores the outer context
        assert tracing.capture()["span_id"] == root.span_id
    assert tracing.capture() is None
    spans, dropped = tracing.RECORDER.drain()
    assert [s["name"] for s in spans] == ["child", "root"]
    assert dropped == 0


def test_span_error_recorded_from_exception(local_tracing):
    tracing = local_tracing
    with pytest.raises(ValueError):
        with tracing.get_tracer().start_span("boom"):
            raise ValueError("nope")
    spans, _ = tracing.RECORDER.drain()
    assert spans[0]["error"] == "ValueError: nope"


def test_flight_recorder_bounded_under_span_storm(local_tracing):
    """Memory stays flat: the ring never exceeds its cap, drops are
    counted, and error spans survive drop-oldest."""
    tracing = local_tracing
    tracing.RECORDER.resize(64)
    err = tracing.get_tracer().start_span("err")
    err.end(error="kept")
    for i in range(5000):
        with tracing.get_tracer().start_span("storm"):
            pass
    stats = tracing.RECORDER.stats()
    assert stats["buffered"] <= 64 + tracing.FlightRecorder.ERROR_CAP
    assert stats["dropped"] >= 5000 - 64
    spans, dropped = tracing.RECORDER.drain()
    assert any(s["error"] == "kept" for s in spans)
    assert dropped >= 5000 - 64
    assert len(tracing.RECORDER) == 0  # drained: memory released


def test_traceparent_round_trip(local_tracing):
    tracing = local_tracing
    with tracing.get_tracer().start_span("r") as r:
        hdr = tracing.format_traceparent()
    ctx = tracing.parse_traceparent(hdr)
    assert ctx == {"trace_id": r.trace_id, "span_id": r.span_id,
                   "sampled": True}
    assert tracing.parse_traceparent(None) is None
    assert tracing.parse_traceparent("00-bad") is None
    assert tracing.parse_traceparent("00-zz-zz-zz") is None
    unsampled = tracing.format_traceparent(
        {"trace_id": "a" * 32, "span_id": "b" * 16, "sampled": False})
    assert unsampled.endswith("-00")
    assert tracing.parse_traceparent(unsampled)["sampled"] is False


# --------------------------------------------------------------------- #
# Chrome trace-event export
# --------------------------------------------------------------------- #


def _validate_chrome(obj):
    """Minimal trace-event schema check: the fields Perfetto's legacy
    JSON importer requires, typed correctly."""
    assert set(obj) >= {"traceEvents", "displayTimeUnit"}
    for ev in obj["traceEvents"]:
        assert ev["ph"] in ("X", "M")
        assert isinstance(ev["name"], str)
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        if ev["ph"] == "X":
            assert isinstance(ev["ts"], float) and ev["dur"] >= 0.0
            assert "args" in ev
        else:
            assert ev["name"] in ("process_name", "thread_name")
            assert "name" in ev["args"]


def test_chrome_export_schema_and_edges(local_tracing):
    import json

    from ray_tpu.observability import chrome_trace_events

    tracing = local_tracing
    with tracing.get_tracer().start_span("parent"):
        with tracing.get_tracer().start_span("kid"):
            pass
    spans, _ = tracing.RECORDER.drain()
    for s in spans:
        s["proc"] = "proc-a"
    out = chrome_trace_events(spans)
    json.dumps(out)  # encodable
    _validate_chrome(out)
    xs = {e["args"]["span_id"]: e for e in out["traceEvents"]
          if e["ph"] == "X"}
    kid = next(e for e in xs.values() if e["name"] == "kid")
    parent = xs[kid["args"]["parent_id"]]
    assert parent["name"] == "parent"
    assert parent["args"]["trace_id"] == kid["args"]["trace_id"]
    # one track per process: both spans share the pid, and a metadata
    # event names it
    assert parent["pid"] == kid["pid"]
    meta = [e for e in out["traceEvents"] if e["ph"] == "M"]
    assert any(e["name"] == "process_name"
               and e["args"]["name"] == "proc-a" for e in meta)


def test_span_tree_nesting(local_tracing):
    from ray_tpu.observability import span_tree

    tracing = local_tracing
    with tracing.get_tracer().start_span("a") as a:
        with tracing.get_tracer().start_span("b"):
            pass
        with tracing.get_tracer().start_span("c"):
            pass
    spans, _ = tracing.RECORDER.drain()
    tree = span_tree(spans, a.trace_id)
    assert tree["span_count"] == 3
    (root,) = tree["roots"]
    assert root["name"] == "a"
    assert [c["name"] for c in root["children"]] == ["b", "c"]


def test_failed_flush_restores_drained_spans(local_tracing):
    """A GCS hiccup during the pusher flush must not silently lose the
    drained spans (or their drop accounting): they go back into the
    recorder for the next period."""
    from ray_tpu.util.metrics import MetricsPusher

    tracing = local_tracing
    err = tracing.get_tracer().start_span("err")
    err.end(error="keep me")
    with tracing.get_tracer().start_span("ok"):
        pass

    class DeadGcs:
        def call(self, *a, **k):
            raise ConnectionError("gcs down")

    pusher = MetricsPusher(DeadGcs(), reporter_id="t")
    pusher.flush()  # swallows the failure...
    spans, dropped = tracing.RECORDER.drain()
    # ...but the spans survived for the next cadence.
    assert {s["name"] for s in spans} == {"err", "ok"}
    assert any(s["error"] == "keep me" for s in spans)


# --------------------------------------------------------------------- #
# Metrics satellites
# --------------------------------------------------------------------- #


def test_registry_reregister_keeps_accumulated_series():
    """Satellite regression: re-constructing a same-name same-type
    metric (a re-created deployment) must keep the accumulated series,
    not silently reset it."""
    from ray_tpu.util import metrics as m

    name = f"test_rereg_{time.monotonic_ns()}"
    c1 = m.Counter(name, "d")
    c1.inc(3)
    c2 = m.Counter(name, "d")  # re-construction
    c2.inc(4)
    snap = next(s for s in m.GLOBAL_REGISTRY.snapshot()
                if s["name"] == name)
    assert snap["series"][0][1] == 7.0  # 3 + 4 accumulated
    c1.inc(1)  # both instances share the same series
    snap = next(s for s in m.GLOBAL_REGISTRY.snapshot()
                if s["name"] == name)
    assert snap["series"][0][1] == 8.0
    with pytest.raises(ValueError):
        m.Gauge(name, "type mismatch")
    hname = f"test_rereg_h_{time.monotonic_ns()}"
    h1 = m.Histogram(hname, "d", boundaries=[1, 2])
    h1.observe(1.5)
    h2 = m.Histogram(hname, "d", boundaries=[1, 2])
    h2.observe(0.5)
    snap = next(s for s in m.GLOBAL_REGISTRY.snapshot()
                if s["name"] == hname)
    assert snap["series"][0][1]["count"] == 2
    with pytest.raises(ValueError):
        m.Histogram(hname, "d", boundaries=[1, 2, 3])


def _mini_gcs():
    from ray_tpu.core.gcs import GcsServer

    return GcsServer(port=0)


def test_gcs_expires_stale_and_dead_node_reporters():
    """Satellite regression: a reporter that stops flushing (or whose
    node died) must drop out of /metrics, and the expiry is counted by
    the metrics_stale_reporters gauge."""
    from ray_tpu.core.common import NodeInfo
    from ray_tpu.core.ids import NodeID

    gcs = _mini_gcs()
    try:
        snap = [{"name": "m", "kind": "gauge", "description": "",
                 "series": [[[], 1.0]]}]
        now = time.time()
        gcs.handle_metrics_report(None, {
            "reporter": "live", "metrics": snap, "ts": now,
            "period_s": 2.0})
        gcs.handle_metrics_report(None, {
            "reporter": "silent", "metrics": snap, "ts": now - 60,
            "period_s": 2.0})
        dead = NodeID.from_random()
        gcs.nodes[dead] = NodeInfo(node_id=dead, address="x",
                                   object_manager_address="x",
                                   session_suffix="s", state="DEAD")
        gcs.handle_metrics_report(None, {
            "reporter": "on-dead-node", "metrics": snap, "ts": now,
            "period_s": 2.0, "node": dead.hex()})
        live = gcs._live_metrics()
        assert "live" in live
        assert "silent" not in live          # stopped flushing
        assert "on-dead-node" not in live    # owning node is DEAD
        gauge = next(s for s in live["gcs"]
                     if s["name"] == "metrics_stale_reporters")
        assert gauge["series"][0][1] == 2.0
        # And the rendered exposition carries it.
        text = gcs.handle_metrics_prometheus(None)["text"]
        assert "metrics_stale_reporters" in text
    finally:
        gcs.stop()


def test_gcs_timeline_window_and_limit_caps():
    """/api/timeline's ?window= / ?limit= must bound what the JSON
    encoder sees, and GCS-side drop-oldest must bound the store."""
    from ray_tpu.core.config import GLOBAL_CONFIG

    gcs = _mini_gcs()
    try:
        now = time.time()
        spans = [{"name": f"s{i}", "trace_id": "t", "span_id": f"{i}",
                  "parent_id": None, "start": now - i, "end": now - i,
                  "thread": "main", "attrs": None, "error": None}
                 for i in range(100)]
        gcs.handle_metrics_report(None, {
            "reporter": "r", "metrics": [], "ts": now, "spans": spans})
        out = gcs.handle_trace_timeline(None, {})
        assert len(out["spans"]) == 100
        out = gcs.handle_trace_timeline(None, {"window_s": 10.5})
        assert all(s["end"] >= now - 10.5 for s in out["spans"])
        assert 0 < len(out["spans"]) < 100
        out = gcs.handle_trace_timeline(None, {"limit": 7})
        assert len(out["spans"]) == 7 and out["truncated"] == 93
        # store cap: drop-oldest with a counter
        GLOBAL_CONFIG._overrides["trace_gcs_max_spans"] = 50
        try:
            gcs.handle_metrics_report(None, {
                "reporter": "r", "metrics": [], "ts": now, "spans": spans})
            assert len(gcs.trace_spans) == 50
            assert gcs.trace_dropped >= 100
        finally:
            GLOBAL_CONFIG._overrides.pop("trace_gcs_max_spans", None)
    finally:
        gcs.stop()


# --------------------------------------------------------------------- #
# Cross-process propagation (cluster)
# --------------------------------------------------------------------- #


@pytest.fixture(scope="module")
def traced_cluster():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4,
                 _system_config={"tracing_enabled": True,
                                 "trace_sample_rate": 1.0})
    created = ray_tpu._global_runtime
    yield
    if ray_tpu._global_runtime is created:
        ray_tpu.shutdown()
    _disable_local()


def _trace_spans(trace_id, want_names, timeout=25.0):
    """Flush the driver recorder and poll the GCS until every wanted
    span name is stored (worker pushers flush on a 2s cadence)."""
    import ray_tpu

    rt = ray_tpu._global_runtime
    deadline = time.time() + timeout
    spans = []
    while time.time() < deadline:
        rt._metrics_pusher.flush()
        spans = rt.gcs.call("trace_get", {"trace_id": trace_id})["spans"]
        if want_names <= {s["name"] for s in spans}:
            return spans
        time.sleep(0.4)
    raise AssertionError(
        f"wanted {want_names}, got {sorted({s['name'] for s in spans})}")


def test_task_propagation_one_trace(traced_cluster):
    import ray_tpu

    tracing = _tracing()

    @ray_tpu.remote
    def child():
        return tracing.current_ctx()

    @ray_tpu.remote
    def parent():
        return tracing.current_ctx(), ray_tpu.get(child.remote())

    with tracing.get_tracer().start_span("test.task.root") as root:
        pctx, cctx = ray_tpu.get(parent.remote())
    assert pctx["trace_id"] == root.trace_id
    assert cctx["trace_id"] == root.trace_id
    assert pctx["sampled"] and cctx["sampled"]
    spans = _trace_spans(root.trace_id, {"test.task.root", "task.run"})
    runs = [s for s in spans if s["name"] == "task.run"]
    assert len(runs) >= 2  # parent and child tasks
    # parent edges resolve: the parent task's span hangs off the root
    by_id = {s["span_id"]: s for s in spans}
    assert any(by_id.get(s["parent_id"], {}).get("name")
               == "test.task.root" for s in runs)


def test_actor_propagation_one_trace(traced_cluster):
    import ray_tpu

    tracing = _tracing()

    @ray_tpu.remote
    class Probe:
        def ctx(self):
            return tracing.current_ctx()

    probe = Probe.remote()
    ray_tpu.get(probe.ctx.remote())  # actor up before the traced call
    with tracing.get_tracer().start_span("test.actor.root") as root:
        actx = ray_tpu.get(probe.ctx.remote())
    assert actx["trace_id"] == root.trace_id
    spans = _trace_spans(root.trace_id, {"actor.call"})
    call = next(s for s in spans if s["name"] == "actor.call")
    assert call["attrs"]["method"] == "ctx"


def test_collective_propagation_one_trace(traced_cluster):
    import ray_tpu

    tracing = _tracing()

    # Actors, not tasks: each rank needs its own worker process (two
    # plain tasks can pipeline onto ONE leased worker, and a collective
    # op parked on rank 0 would starve rank 1 queued behind it).
    @ray_tpu.remote
    class Member:
        def run(self, rank):
            from ray_tpu import collective

            group = collective.init_collective_group(
                2, rank, group_name="trace-grp")
            out = group.allreduce(np.ones(8, np.float32))
            group.leave()
            return float(np.sum(out))

    members = [Member.remote() for _ in range(2)]
    with tracing.get_tracer().start_span("test.coll.root") as root:
        totals = ray_tpu.get([m.run.remote(r)
                              for r, m in enumerate(members)], timeout=60)
    assert totals == [16.0, 16.0]
    # Both ranks flush on their own 2s cadence: poll until both arrive.
    import ray_tpu as _rt

    deadline = time.time() + 25
    ops = []
    while time.time() < deadline:
        spans = _rt._global_runtime.gcs.call(
            "trace_get", {"trace_id": root.trace_id})["spans"]
        ops = [s for s in spans if s["name"] == "collective.allreduce"]
        if {s["attrs"]["rank"] for s in ops} == {0, 1}:
            break
        time.sleep(0.4)
    assert {s["attrs"]["rank"] for s in ops} == {0, 1}
    assert {s["proc"] for s in ops if s["proc"]}  # recorded by workers
    assert all(s["trace_id"] == root.trace_id for s in ops)


def test_serve_http_llm_trace_spans_processes_and_ttft(traced_cluster):
    """The acceptance path: ONE HTTP request against the LLM deployment
    yields a single trace crossing the client/driver, proxy and replica
    processes (engine phases on their own thread track), with TTFT
    decomposed into queue/prefill/decode — exported as valid Chrome
    trace-event JSON."""
    import json
    import urllib.request

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.inference import LLMServer
    from ray_tpu.observability import chrome_trace_events

    tracing = _tracing()
    serve.run(LLMServer.options(num_replicas=1).bind(
        "tiny", 128, 4,
        engine_config={"use_jit": False, "batch_slots": 2,
                       "block_size": 8, "num_blocks": 32,
                       "max_blocks_per_seq": 8, "prefill_chunk": 8}))
    try:
        port = serve.http_port()
        with tracing.get_tracer().start_span("client.request") as root:
            hdr = tracing.format_traceparent()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/LLMServer",
            data=json.dumps({"ids": [1, 2, 3],
                             "max_new_tokens": 4}).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": hdr})
        with urllib.request.urlopen(req, timeout=120) as resp:
            body = json.loads(resp.read())
        assert len(body["result"]["ids"]) == 7

        # The fast data plane dispatches direct (serve.direct replaces
        # the classic serve.route/serve.dispatch pair on this path).
        # client.request is in the wait set on purpose: the driver's own
        # flush lands asynchronously, and the >=3-process assertion below
        # needs the driver's span stored, not merely flushed (the poll
        # returning on worker spans alone made this flake under load).
        want = {"client.request", "serve.http", "serve.direct",
                "serve.replica", "engine.queue", "engine.prefill",
                "engine.decode"}
        spans = _trace_spans(root.trace_id, want, timeout=40.0)
        assert all(s["trace_id"] == root.trace_id for s in spans)
        # ONE trace, >= 3 OS processes (driver client, proxy worker,
        # replica worker) and >= 4 tracks once the engine thread's is
        # counted — proxy, router (in-proxy), replica, engine.
        procs = {s["proc"] for s in spans}
        assert len(procs) >= 3, procs
        tracks = {(s["proc"], s["thread"]) for s in spans}
        assert len(tracks) >= 4, tracks
        # TTFT decomposition is contiguous: queue ends where prefill
        # begins; prefill ends where decode begins.
        phases = {s["name"]: s for s in spans
                  if s["name"].startswith("engine.")}
        assert phases["engine.queue"]["end"] == \
            pytest.approx(phases["engine.prefill"]["start"], abs=1e-6)
        assert phases["engine.prefill"]["end"] == \
            pytest.approx(phases["engine.decode"]["start"], abs=1e-6)
        assert phases["engine.decode"]["attrs"]["tokens"] == 4
        # Valid Chrome trace-event JSON with resolvable span edges.
        out = chrome_trace_events(spans)
        json.dumps(out)
        _validate_chrome(out)
        xs = {e["args"]["span_id"]: e for e in out["traceEvents"]
              if e["ph"] == "X"}
        http = next(e for e in xs.values() if e["name"] == "serve.http")
        assert xs[http["args"]["parent_id"]]["name"] == "client.request"
    finally:
        serve.shutdown()


def test_rpc_wire_ctx_suppresses_resampling(traced_cluster):
    """An unsampled context crosses the wire as the 0 marker: the far
    side must NOT root a fresh sampled trace mid-request."""
    import ray_tpu

    tracing = _tracing()

    @ray_tpu.remote
    def probe():
        ctx = tracing.current_ctx()
        return None if ctx is None else ctx.get("sampled")

    tok = tracing.activate({"trace_id": "f" * 32, "span_id": "e" * 16,
                            "sampled": False})
    try:
        sampled = ray_tpu.get(probe.remote())
    finally:
        tracing.deactivate(tok)
    assert sampled is False
