"""Ray Train equivalent: JaxTrainer end-to-end on CPU workers.

Mirrors the reference's `python/ray/train/tests/test_backend.py` strategy:
real worker actors, real backend setup, results streamed via session.report.
"""

import os

import numpy as np
import pytest


def _sgd_loop(config):
    """Tiny numpy 'training': report decreasing loss + a checkpoint."""
    from ray_tpu import train
    from ray_tpu.train import Checkpoint, session

    rank = session.get_world_rank()
    world = session.get_world_size()
    w = 10.0
    start = 0
    ckpt = session.get_checkpoint()
    if ckpt is not None:
        state = ckpt.to_dict()
        w = state["w"]
        start = state["step"] + 1
    for step in range(start, config.get("steps", 4)):
        w = w - 0.5 * w  # "gradient step"
        session.report(
            {"loss": abs(w), "step": step, "rank": rank, "world": world},
            checkpoint=Checkpoint.from_dict({"w": w, "step": step})
            if rank == 0 else None,
        )


def test_jax_trainer_e2e_two_workers(ray_start_shared, tmp_path):
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig
    from ray_tpu.train.backend import JaxConfig

    trainer = JaxTrainer(
        _sgd_loop,
        train_loop_config={"steps": 3},
        # No jax.distributed for the numpy loop: keeps the e2e fast.
        jax_config=JaxConfig(distributed=False),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="e2e", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["loss"] < 10.0
    assert result.metrics["world"] == 2
    assert len(result.metrics_history) == 3
    assert result.checkpoint is not None
    state = result.checkpoint.to_dict()
    assert state["step"] == 2


def test_trainer_restore_resumes_from_checkpoint(ray_start_shared, tmp_path):
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig
    from ray_tpu.train.backend import JaxConfig

    kwargs = dict(
        train_loop_config={"steps": 2},
        jax_config=JaxConfig(distributed=False),
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="resume", storage_path=str(tmp_path)),
    )
    r1 = JaxTrainer(_sgd_loop, **kwargs).fit()
    assert r1.error is None
    exp_dir = r1.path

    kwargs["train_loop_config"] = {"steps": 4}
    restored = JaxTrainer.restore(exp_dir, _sgd_loop, **kwargs)
    assert restored.resume_from_checkpoint is not None
    r2 = restored.fit()
    assert r2.error is None
    # Resumed from step 1 -> ran steps 2,3 only.
    assert [m["step"] for m in r2.metrics_history] == [2, 3]


def test_worker_group_cpu_autoscale(ray_start_shared):
    """More CPU requested than the cluster has -> fractional auto-fit."""
    from ray_tpu.train.worker_group import WorkerGroup

    wg = WorkerGroup(num_workers=2, resources_per_worker={"CPU": 8.0})
    try:
        infos = wg.execute(lambda: os.getpid())
        assert len(set(infos)) == 2
    finally:
        wg.shutdown()


def test_train_failure_surfaces_error(ray_start_shared, tmp_path):
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig
    from ray_tpu.train.backend import JaxConfig

    def bad_loop(config):
        raise RuntimeError("boom in train loop")

    result = JaxTrainer(
        bad_loop,
        jax_config=JaxConfig(distributed=False),
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="fail", storage_path=str(tmp_path)),
    ).fit()
    assert result.error is not None
    assert "train loop failed" in str(result.error) or "boom" in str(result.error)


def test_checkpoint_manager_keep_best(tmp_path):
    from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager

    mgr = CheckpointManager(str(tmp_path), num_to_keep=2,
                            score_attribute="acc", score_order="max")
    for i, acc in enumerate([0.1, 0.9, 0.5]):
        mgr.register(Checkpoint.from_dict({"i": i}), {"acc": acc})
    best = mgr.best_checkpoint()
    assert best.to_dict()["i"] == 1
    # Only 2 kept on disk.
    kept = [d for d in os.listdir(tmp_path) if d.startswith("checkpoint_")]
    assert len(kept) == 2


def test_checkpoint_uri_roundtrip(tmp_path):
    from ray_tpu.train import Checkpoint

    ck = Checkpoint.from_dict({"w": 7})
    uri = ck.to_uri(f"file://{tmp_path}/ck")
    assert uri.startswith("file://")
    back = Checkpoint.from_uri(uri)
    assert back.to_dict() == {"w": 7}
    assert back.uri == uri


def test_batch_predictor_scores_dataset(ray_start_shared, tmp_path):
    """BatchPredictor: checkpointed MLP scores a Dataset through the
    actor-pool map operator (reference train/batch_predictor.py)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu import data as rd
    from ray_tpu.models.mlp import MLP
    from ray_tpu.train import BatchPredictor, Checkpoint, JaxPredictor

    model = MLP(features=(8, 3))
    rng = jax.random.PRNGKey(0)
    x0 = jnp.zeros((1, 4))
    params = model.init(rng, x0)
    ck = Checkpoint.from_pytree(params, path=str(tmp_path / "ck"))

    n = 64
    xs = np.random.default_rng(0).normal(size=(n, 4)).astype(np.float32)
    ds = rd.from_items([{"x": xs[i], "idx": i} for i in range(n)])

    bp = BatchPredictor.from_checkpoint(ck, JaxPredictor, model=model)
    out = bp.predict(ds, batch_size=16, max_scoring_workers=2,
                     keep_columns=("idx",))
    rows = out.take_all()
    assert len(rows) == n
    # Batch rows carry predictions + passthrough column.
    got = {int(r["idx"]): r["predictions"] for r in rows}
    expected = np.asarray(model.apply(params, xs))
    for i in range(n):
        # Scoring actors may run on the ambient accelerator (TPU matmuls
        # round through bfloat16); compare at bf16 tolerance.
        np.testing.assert_allclose(got[i], expected[i], rtol=0.1, atol=0.02)


@pytest.mark.slow  # ~20s: spawns a gloo process group and trains for real
def test_torch_trainer_ddp_gloo(ray_start_shared, tmp_path):
    """TorchTrainer forms a gloo process group across workers and DDP
    synchronizes gradients (reference TorchTrainer / _TorchBackend)."""
    from ray_tpu.train import RunConfig, ScalingConfig, TorchTrainer
    from ray_tpu.train import session as _session  # noqa: F401

    def loop(config):
        import numpy as np
        import torch
        import torch.distributed as dist
        from torch.nn.parallel import DistributedDataParallel as DDP

        from ray_tpu.train import session

        rank = dist.get_rank()
        world = dist.get_world_size()
        torch.manual_seed(1234)  # same init on every rank
        model = torch.nn.Linear(4, 1)
        ddp = DDP(model)
        opt = torch.optim.SGD(ddp.parameters(), lr=0.1)
        # Different data per rank: DDP's allreduce must still produce
        # identical updated params everywhere.
        g = torch.Generator().manual_seed(rank)
        x = torch.randn(16, 4, generator=g)
        y = torch.randn(16, 1, generator=g)
        for _ in range(3):
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(ddp(x), y)
            loss.backward()
            opt.step()
        flat = torch.cat([p.detach().reshape(-1)
                          for p in model.parameters()])
        gathered = [torch.zeros_like(flat) for _ in range(world)]
        dist.all_gather(gathered, flat)  # collective over the gloo group
        session.report({
            "rank": rank, "world": world,
            "max_param_diff": float(
                (gathered[0] - gathered[1]).abs().max()),
            "loss": float(loss)})

    trainer = TorchTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="torch_ddp", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["world"] == 2
    # DDP gradient sync: both ranks hold identical parameters (the
    # all_gather itself also proves the gloo group works end to end).
    assert result.metrics["max_param_diff"] < 1e-6
