"""Wrapper trainers: HF Transformers (installed) + gated GBDT.

Reference behavior: `python/ray/train/huggingface/transformers/`
(TransformersTrainer + RayTrainReportCallback) and
`train/{xgboost,lightgbm}` trainers.
"""

import numpy as np
import pytest


def test_transformers_trainer_runs_tiny_model(ray_start_shared, tmp_path):
    from ray_tpu.train import (
        RunConfig,
        ScalingConfig,
        TransformersTrainer,
    )

    def loop(config):
        import torch
        from transformers import GPT2Config, GPT2LMHeadModel

        from ray_tpu.train import session

        cfg = GPT2Config(n_embd=32, n_layer=1, n_head=2, n_positions=32,
                         vocab_size=128)
        model = GPT2LMHeadModel(cfg)
        opt = torch.optim.AdamW(model.parameters(), lr=1e-3)
        ids = torch.randint(0, 128, (4, 16))
        for step in range(2):
            out = model(input_ids=ids, labels=ids)
            out.loss.backward()
            opt.step()
            opt.zero_grad()
            session.report({"loss": float(out.loss), "step": step})

    trainer = TransformersTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="hf_tiny", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert np.isfinite(result.metrics["loss"])
    assert result.metrics["step"] == 1


def test_prepare_trainer_reports_hf_logs(ray_start_shared, tmp_path):
    """prepare_trainer's callback forwards transformers.Trainer logs
    into session.report."""
    from ray_tpu.train import (
        RunConfig,
        ScalingConfig,
        TransformersTrainer,
    )

    def loop(config):
        import torch
        from transformers import (
            GPT2Config,
            GPT2LMHeadModel,
            Trainer,
            TrainingArguments,
        )

        from ray_tpu.train import prepare_trainer

        cfg = GPT2Config(n_embd=32, n_layer=1, n_head=2, n_positions=32,
                         vocab_size=128)
        model = GPT2LMHeadModel(cfg)

        class DS(torch.utils.data.Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                ids = torch.randint(0, 128, (16,))
                return {"input_ids": ids, "labels": ids}

        args = TrainingArguments(
            output_dir=config["out"], max_steps=3, logging_steps=1,
            per_device_train_batch_size=4, report_to=[],
            disable_tqdm=True, use_cpu=True)
        hf = Trainer(model=model, args=args, train_dataset=DS())
        prepare_trainer(hf)
        hf.train()

    trainer = TransformersTrainer(
        loop,
        train_loop_config={"out": str(tmp_path / "hf_out")},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="hf_cb", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    # HF logged at least one loss line through the callback.
    assert "loss" in result.metrics or "train_loss" in result.metrics


def test_gbdt_trainers_gated():
    """Without xgboost/lightgbm installed, construction fails with a
    clear error naming the missing package."""
    from ray_tpu.train import LightGBMTrainer, XGBoostTrainer

    for cls, pkg in ((XGBoostTrainer, "xgboost"),
                     (LightGBMTrainer, "lightgbm")):
        try:
            import importlib

            importlib.import_module(pkg)
            pytest.skip(f"{pkg} installed; gate cannot fire")
        except ImportError:
            pass
        with pytest.raises(ImportError, match=pkg):
            cls(params={})
