"""Pipeline & sequence parallelism (ISSUE 20): stage programs, 1F1B,
collective p2p, partition rules, resharded checkpoints, elastic runs.

The parity spine: in f32, splitting the llama across jit boundaries and
chaining per-stage VJPs is BITWISE equal to the monolithic
value_and_grad — so every schedule/width/transport comparison here
asserts exact equality, not tolerances. p2p tests drive ranks as
threads over an in-process Cluster (the test_collective harness);
elastic tests run real worker processes under BackendExecutor.
"""

import os
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.collective import CollectiveGroup, RayletTransport
from ray_tpu.core.config import GLOBAL_CONFIG

from conftest import assert_compiles_once

STALL_S = 10.0


def _tree_equal(a, b):
    import jax

    return bool(jax.tree.all(jax.tree.map(
        lambda x, y: bool(np.array_equal(np.asarray(x), np.asarray(y))),
        a, b)))


def _tiny_cfg(**over):
    from ray_tpu.train.pipeline import tiny_pipeline_config

    return tiny_pipeline_config(**over)


# --------------------------------------------------------------------------- #
# Collective p2p (send / isend / recv)
# --------------------------------------------------------------------------- #


@pytest.fixture()
def p2p_cluster():
    ray_tpu.shutdown()
    saved = dict(GLOBAL_CONFIG._overrides)
    GLOBAL_CONFIG._overrides.update({
        "collective_stall_timeout_s": STALL_S,
        "collective_inline_max_bytes": 1024,
        "collective_p2p_ack_window": 2,
        "rpc_connect_timeout_s": 2.0,
    })
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    try:
        yield cluster
    finally:
        cluster.shutdown()
        GLOBAL_CONFIG._overrides.clear()
        GLOBAL_CONFIG._overrides.update(saved)


def _run_pair(cluster, fn, join_s=60.0):
    """fn(rank, group) for ranks 0/1 on threads; returns (results, errs)."""
    results, errors = [None, None], [None, None]

    def run(rank):
        try:
            group = CollectiveGroup(
                "p2p", 2, rank,
                transport=RayletTransport(cluster.raylets[rank]))
            try:
                results[rank] = fn(rank, group)
            finally:
                if rank == 0:
                    group.destroy()
                else:
                    group.leave()
        except Exception as e:  # noqa: BLE001 — asserted by callers
            errors[rank] = e

    threads = [threading.Thread(target=run, args=(r,)) for r in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(join_s)
    assert not any(t.is_alive() for t in threads), "p2p rank thread hung"
    return results, errors


def test_p2p_ordering_mixed_payloads(p2p_cluster):
    """Messages on one channel arrive in send order across the
    inline/object size boundary, and channels with different tags never
    interleave."""
    def fn(rank, group):
        if rank == 0:
            for i in range(6):
                # odd sends cross the 1KB inline ceiling -> object path
                size = 8 if i % 2 == 0 else 4096
                group.send({"i": i, "data": np.full(size, i, np.uint8)}, 1)
            for i in range(3):
                group.send(("other", i), 1, tag="side")
            group.barrier()     # receiver drains before rank 0 destroys
            return None
        got = [group.recv(0) for _ in range(6)]
        side = [group.recv(0, tag="side") for _ in range(3)]
        group.barrier()
        return got, side

    results, errors = _run_pair(p2p_cluster, fn)
    assert not any(errors), errors
    got, side = results[1]
    assert [g["i"] for g in got] == list(range(6))
    for g in got:
        assert (g["data"] == g["i"]).all()
    assert side == [("other", i) for i in range(3)]


def test_p2p_isend_call_order_survives_thread_races(p2p_cluster):
    """isend reserves the channel seq in the CALLER: many overlapping
    background posts still deliver in call order."""
    def fn(rank, group):
        if rank == 0:
            handles = [group.isend(np.full(4096, i, np.int32), 1)
                       for i in range(10)]
            for h in handles:
                h.wait(30.0)
            group.barrier()     # receiver drains before rank 0 destroys
            return None
        out = []
        for _ in range(10):
            time.sleep(0.01)    # receiver lags: window must flow-control
            out.append(int(group.recv(0)[0]))
        group.barrier()
        return out

    results, errors = _run_pair(p2p_cluster, fn)
    assert not any(errors), errors
    assert results[1] == list(range(10))


def test_p2p_bidirectional_streams_no_deadlock(p2p_cluster):
    """The 1F1B wire pattern: both ranks stream object-path messages at
    each other through a window of 2 while also receiving. A send
    blocking on its drain ack must not wedge the reverse channel."""
    n = 8

    def fn(rank, group):
        peer = 1 - rank
        got = []

        def pump():
            for i in range(n):
                group.send(np.full(4096, i * 10 + rank, np.int32), peer,
                           tag="fwd" if rank == 0 else "bwd")

        t = threading.Thread(target=pump)
        t.start()
        for _ in range(n):
            got.append(int(group.recv(peer,
                                      tag="bwd" if rank == 0 else "fwd")[0]))
        t.join(30.0)
        group.barrier()
        return got

    results, errors = _run_pair(p2p_cluster, fn)
    assert not any(errors), errors
    assert results[0] == [i * 10 + 1 for i in range(n)]
    assert results[1] == [i * 10 for i in range(n)]


# --------------------------------------------------------------------------- #
# Stage-split parity (bitwise, f32)
# --------------------------------------------------------------------------- #


@pytest.mark.slow  # ~10s: compiles 3 stage program sets AND the monolith
def test_stage_chain_bitwise_vs_monolithic_grad():
    """pp=3 chained stage programs (fwd / fused last / middle+first bwd)
    reproduce the monolithic jit value_and_grad BIT FOR BIT — including
    a middle stage, whose bwd differentiates both params and input."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.llama import Llama, split_stage_params
    from ray_tpu.train.pipeline import (
        build_stage_programs,
        token_xent,
    )

    cfg = _tiny_cfg(n_layer=3)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                             cfg.vocab_size)
    targets = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0,
                                 cfg.vocab_size)
    model = Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]

    @jax.jit
    def mono(p, x, t):
        return jax.value_and_grad(
            lambda pp_: token_xent(model.apply({"params": pp_}, x), t))(p)

    loss_ref, grad_ref = mono(params, ids, targets)

    pp = 3
    progs = [build_stage_programs(cfg, s, pp) for s in range(pp)]
    stages = split_stage_params(params, cfg, pp)
    y0 = progs[0].fwd(stages[0], ids)
    y1 = progs[1].fwd(stages[1], y0)
    loss, gp2, gy1 = progs[2].fwdbwd(stages[2], y1, targets)
    gp1, gy0 = progs[1].bwd(stages[1], y0, gy1)
    gp0 = progs[0].bwd(stages[0], ids, gy0)

    assert np.array_equal(np.asarray(loss), np.asarray(loss_ref))
    grad_stages = split_stage_params(grad_ref, cfg, pp)
    assert _tree_equal(gp0, grad_stages[0])
    assert _tree_equal(gp1, grad_stages[1])
    assert _tree_equal(gp2, grad_stages[2])


def test_pp2_training_bitwise_vs_pp1_and_compiles_once():
    """Three adam steps at pp=2 match pp=1 step-for-step (losses AND
    merged weights bitwise) with exactly one compile per stage
    program — the zero-per-step-recompile acceptance bar."""
    from ray_tpu.train.pipeline import LocalPipelineTrainer, seeded_batch

    cfg = _tiny_cfg()
    t1 = LocalPipelineTrainer(cfg, pp=1, num_microbatches=2, seed=0)
    t2 = LocalPipelineTrainer(cfg, pp=2, num_microbatches=2, seed=0)
    for step in range(3):
        ids, tg = seeded_batch(0, step, 4, 16, cfg.vocab_size)
        m1 = t1.train_step(ids, tg)
        m2 = t2.train_step(ids, tg)
        assert m1["loss"] == m2["loss"], (step, m1, m2)
    assert _tree_equal(t1.merged_params(), t2.merged_params())
    for trainer in (t1, t2):
        for name, fn in trainer.compile_counters().items():
            assert_compiles_once(fn, context=f"pp={trainer.pp} {name}")


def test_1f1b_and_sequential_schedules_bitwise_equal():
    """Same microbatch accumulation order => the overlapped schedule and
    the serialized A/B produce identical losses and weights; the
    schedules differ only in warmup depth (call counts prove both ran
    every microbatch exactly once per direction)."""
    from ray_tpu.train.pipeline import (
        LocalPipelineTrainer,
        analytic_bubble,
        seeded_batch,
    )

    cfg = _tiny_cfg()
    m = 4
    a = LocalPipelineTrainer(cfg, pp=2, num_microbatches=m, seed=0,
                             schedule="1f1b")
    b = LocalPipelineTrainer(cfg, pp=2, num_microbatches=m, seed=0,
                             schedule="sequential")
    for step in range(2):
        ids, tg = seeded_batch(0, step, 8, 16, cfg.vocab_size)
        ma = a.train_step(ids, tg)
        mb = b.train_step(ids, tg)
        assert ma["loss"] == mb["loss"]
    assert _tree_equal(a.merged_params(), b.merged_params())
    for trainer in (a, b):
        for st in trainer.last_result.stage_stats:
            assert st.fwd_calls == m and st.bwd_calls == m
            assert 0.0 <= st.bubble_frac <= 1.0
            assert st.analytic_bubble_frac == analytic_bubble(2, m)
    assert analytic_bubble(2, 4) == pytest.approx(1 / 5)
    assert analytic_bubble(4, 8) == pytest.approx(3 / 11)
    assert analytic_bubble(1, 4) == 0.0


def test_llama_sp_ring_attention_parity():
    """An "sp" mesh routes llama attention through the ppermute ring;
    outputs match the reference path to fp32 ring-reduction tolerance
    (the ring reorders the softmax accumulation, so this one is
    allclose, not bitwise)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from ray_tpu.models.llama import Llama
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh

    cfg = _tiny_cfg()
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                             cfg.vocab_size)
    model = Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    ref = model.apply({"params": params}, ids)

    mesh = build_mesh(MeshSpec({"sp": 2}), devices=jax.devices()[:2])
    sp_model = Llama(dataclasses.replace(cfg, sp_mesh=mesh))
    out = jax.jit(
        lambda p, x: sp_model.apply({"params": p}, x))(params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=5e-6, rtol=1e-5)


# --------------------------------------------------------------------------- #
# Partition rules
# --------------------------------------------------------------------------- #


def test_match_partition_rules_llama_table():
    """The regex table assigns every llama param a deliberate spec —
    first match wins, scalars replicate, a renamed param raises instead
    of silently replicating."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ray_tpu.models.llama import LLAMA_PARTITION_RULES, Llama
    from ray_tpu.parallel.sharding import match_partition_rules

    cfg = _tiny_cfg()
    ids = np.zeros((1, 8), np.int32)
    params = Llama(cfg).init(jax.random.PRNGKey(0), ids)["params"]
    specs = match_partition_rules(LLAMA_PARTITION_RULES, params)
    flat = {"/".join(str(getattr(k, "key", getattr(k, "name", k)))
                     for k in path): spec
            for path, spec in
            jax.tree_util.tree_flatten_with_path(
                specs, is_leaf=lambda x: isinstance(x, P))[0]}

    def spec_for(fragment):
        hits = [v for k, v in flat.items() if fragment in k]
        assert hits, (fragment, sorted(flat))
        return hits[0]

    assert spec_for("embed") == P("tp")
    assert spec_for("wq/kernel") == P(None, "tp")
    assert spec_for("wo/kernel") == P("tp")
    assert spec_for("w_down/kernel") == P("tp")
    assert spec_for("final_norm") == P()

    with pytest.raises(ValueError, match="no partition rule"):
        match_partition_rules(LLAMA_PARTITION_RULES,
                              {"mystery": {"kernel": np.ones((2, 2))}})


def test_shard_params_by_rules_prunes_absent_axes():
    """One rule table serves every submesh: axes the mesh lacks are
    pruned to replicated (and no trailing-None specs are built)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.parallel.sharding import shard_params_by_rules

    rules = ((r"w$", P(None, "tp")), (r"e$", P("tp")))
    params = {"w": np.ones((4, 8), np.float32),
              "e": np.ones((8, 2), np.float32)}
    tp_mesh = build_mesh(MeshSpec({"tp": 2}), devices=jax.devices()[:2])
    placed = shard_params_by_rules(params, tp_mesh, rules)
    assert placed["w"].sharding.spec == P(None, "tp")
    assert placed["e"].sharding.spec == P("tp")

    sp_mesh = build_mesh(MeshSpec({"sp": 2}), devices=jax.devices()[:2])
    placed = shard_params_by_rules(params, sp_mesh, rules)
    # "tp" absent: pruned to fully-replicated, trailing Nones dropped
    assert placed["w"].sharding.spec == P()
    assert placed["e"].sharding.spec == P()


# --------------------------------------------------------------------------- #
# Resharded stage checkpoints
# --------------------------------------------------------------------------- #


def test_stage_checkpoint_reshard_round_trips(tmp_path):
    """(tp=2, pp=2) save -> restore at (1,1), (4,1) and (1,2): all
    bitwise (raw-byte shard assembly), adam state included."""
    import jax
    import optax

    from ray_tpu.models.llama import (
        Llama,
        shard_stage_params,
        split_stage_params,
    )
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.train.checkpoint import merge_sharded_manifest
    from ray_tpu.train.pipeline import (
        restore_pipeline_stage,
        save_pipeline_stage,
        seeded_batch,
    )

    cfg = _tiny_cfg()
    sample = seeded_batch(0, 0, 2, 16, cfg.vocab_size)[0]
    full = Llama(cfg).init(jax.random.PRNGKey(0), sample)["params"]
    opt = optax.adam(1e-2)
    mesh = build_mesh(MeshSpec({"tp": 2}), devices=jax.devices()[:2])
    stages = split_stage_params(full, cfg, 2)
    path = str(tmp_path / "ck")
    for s in range(2):
        sharded = shard_stage_params(stages[s], mesh)
        save_pipeline_stage(path, {"params": sharded,
                                   "opt": opt.init(sharded)}, s, 2, step=0)
    merge_sharded_manifest(path, 2)

    st = restore_pipeline_stage(path, cfg, 0, 1, opt, sample)
    assert _tree_equal(st["params"], full)

    mesh4 = build_mesh(MeshSpec({"tp": 4}), devices=jax.devices()[:4])
    st = restore_pipeline_stage(path, cfg, 0, 1, opt, sample, mesh=mesh4)
    assert _tree_equal(st["params"], full)
    from jax.sharding import PartitionSpec as P

    embed = st["params"]["embed"]
    leaf = getattr(embed, "value", embed)
    assert leaf.sharding.spec == P("tp")

    for s in range(2):
        st = restore_pipeline_stage(path, cfg, s, 2, opt, sample)
        assert _tree_equal(st["params"], stages[s])


def test_stage_checkpoint_missing_stage_fails_loudly(tmp_path):
    """A merge over a world where one stage never saved must raise, not
    produce a manifest that restores garbage for the absent subtree."""
    import jax
    import optax

    from ray_tpu.models.llama import Llama, split_stage_params
    from ray_tpu.train.checkpoint import merge_sharded_manifest
    from ray_tpu.train.pipeline import save_pipeline_stage, seeded_batch

    cfg = _tiny_cfg()
    sample = seeded_batch(0, 0, 2, 16, cfg.vocab_size)[0]
    full = Llama(cfg).init(jax.random.PRNGKey(0), sample)["params"]
    stage0 = split_stage_params(full, cfg, 2)[0]
    opt = optax.adam(1e-2)
    path = str(tmp_path / "ck")
    save_pipeline_stage(path, {"params": stage0, "opt": opt.init(stage0)},
                        0, 2, step=0)
    with pytest.raises(FileNotFoundError):
        merge_sharded_manifest(path, 2)


def test_replicated_leaves_need_owner_for_stage_saves(tmp_path):
    """The hazard own_replicated=True exists for: a NON-zero rank saving
    a disjoint subtree under SPMD ownership rules writes zero-coverage
    entries for its replicated leaves, and the merge rejects them."""
    import jax.numpy as jnp

    from ray_tpu.train.checkpoint import (
        merge_sharded_manifest,
        save_sharded_pytree,
    )

    tree = {"scale": jnp.ones((4,), jnp.float32)}
    path = str(tmp_path / "ck")
    # rank 1 saves its own subtree but under the SPMD default (rank 0
    # owns replicated leaves) -> empty shard list;  rank 0 has no
    # manifest at all for these keys
    save_sharded_pytree(path, {}, process_index=0, process_count=2)
    save_sharded_pytree(path, tree, process_index=1, process_count=2)
    with pytest.raises(ValueError, match="covers only"):
        merge_sharded_manifest(path, 2)


# --------------------------------------------------------------------------- #
# Elastic pipeline runs (worker processes)
# --------------------------------------------------------------------------- #


def _drain(executor, train_fn, config, experiment_name):
    per_step = {}
    for rnd in executor.run(train_fn, config,
                            experiment_name=experiment_name):
        for r in rnd:
            m = r["metrics"]
            per_step.setdefault(m["step"], {}).update(
                {k: m[k] for k in ("world", "loss") if k in m})
    return per_step


@pytest.mark.slow
def test_pipeline_worker_run_matches_local_bitwise(tmp_path):
    """pp=2 over real worker processes + collective p2p reproduces the
    single-process pp=1 run bitwise, step for step."""
    from ray_tpu.train.backend import BackendConfig
    from ray_tpu.train.backend_executor import BackendExecutor
    from ray_tpu.train.config import ScalingConfig
    from ray_tpu.train.pipeline import (
        LocalPipelineTrainer,
        make_pipeline_train_fn,
        seeded_batch,
    )

    steps = 4
    train_fn = make_pipeline_train_fn(
        steps=steps, microbatches=2, batch=4, seq=16, lr=1e-2, seed=0,
        ckpt_dir=str(tmp_path / "ck"))
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    try:
        ex = BackendExecutor(BackendConfig(), ScalingConfig(num_workers=2))
        ex.start()
        per_step = _drain(ex, train_fn, {}, "pipe_parity")
        ex.shutdown()
    finally:
        ray_tpu.shutdown()

    cfg = _tiny_cfg()
    local = LocalPipelineTrainer(cfg, pp=1, num_microbatches=2, seed=0)
    for step in range(steps):
        ids, tg = seeded_batch(0, step, 4, 16, cfg.vocab_size)
        ref = local.train_step(ids, tg)
        assert per_step[step]["loss"] == ref["loss"], (step, per_step)
        assert per_step[step]["world"] == 2


@pytest.mark.slow
def test_kill_a_stage_resharded_resume_bitwise(tmp_path):
    """Kill one stage's worker mid-run: the gang restarts SHRUNK to
    pp=1 under the recovery deadline, restores the merged (pp=2)
    manifest re-split at the new width, and finishes with weights
    bitwise-equal to an unkilled run at the same step count."""
    import jax
    import optax

    from ray_tpu.train.backend import BackendConfig
    from ray_tpu.train.backend_executor import BackendExecutor
    from ray_tpu.train.config import ScalingConfig
    from ray_tpu.train.pipeline import (
        LocalPipelineTrainer,
        make_pipeline_train_fn,
        restore_pipeline_stage,
        seeded_batch,
    )

    steps = 8
    train_fn = make_pipeline_train_fn(
        steps=steps, microbatches=2, batch=4, seq=16, lr=1e-2, seed=0,
        ckpt_dir=str(tmp_path / "ck"))
    os.environ["RAY_TPU_COLLECTIVE_STALL_TIMEOUT_S"] = "10"
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    deadline = time.monotonic() + 180.0
    try:
        ex = BackendExecutor(BackendConfig(), ScalingConfig(num_workers=2),
                             max_failures=2,
                             elastic_world_fn=lambda fail, world: 1)
        ex.start()

        def killer():
            # wait for a merged checkpoint so the resume is a genuine
            # RESHARD (pp=2 manifest -> pp=1 restore), then kill a rank
            while True:
                ck = ex.latest_checkpoint
                if ck is not None and ck.to_dict().get("step", -1) >= 1:
                    break
                if time.monotonic() > deadline:
                    return
                time.sleep(0.1)
            ray_tpu._global_runtime.raylet.call(
                "chaos_kill_worker", {"draw": 1, "actors_only": True})

        threading.Thread(target=killer, daemon=True).start()
        per_step = _drain(ex, train_fn, {}, "pipe_kill")
        final = ex.latest_checkpoint.to_dict()
        restarts = list(ex.restarts)
        ex.shutdown()
    finally:
        ray_tpu.shutdown()
        os.environ.pop("RAY_TPU_COLLECTIVE_STALL_TIMEOUT_S", None)

    assert time.monotonic() < deadline, "recovery blew the 180s deadline"
    assert restarts and restarts[0]["world_size"] == 1, restarts
    assert final["step"] == steps - 1
    worlds = {s: v["world"] for s, v in per_step.items()}
    assert 2 in worlds.values() and 1 in worlds.values(), worlds

    cfg = _tiny_cfg()
    ref = LocalPipelineTrainer(cfg, pp=1, num_microbatches=2, seed=0)
    for step in range(steps):
        ids, tg = seeded_batch(0, step, 4, 16, cfg.vocab_size)
        ref.train_step(ids, tg)
    sample = seeded_batch(0, 0, 2, 16, cfg.vocab_size)[0]
    st = restore_pipeline_stage(final["path"], cfg, 0, 1, optax.adam(1e-2),
                                sample)
    assert _tree_equal(st["params"], ref.merged_params())
