"""ray_tpu.tune: search spaces, schedulers, controller, restore."""

import os

import pytest

from ray_tpu import tune


def test_variant_generator_grid_and_samples():
    from ray_tpu.tune.search import BasicVariantGenerator

    configs = BasicVariantGenerator(
        {"a": tune.grid_search([1, 2, 3]), "b": tune.choice([10]),
         "c": "fixed"},
        num_samples=2, seed=0).generate()
    assert len(configs) == 6
    assert {c["a"] for c in configs} == {1, 2, 3}
    assert all(c["b"] == 10 and c["c"] == "fixed" for c in configs)


def test_domains_sample_in_range():
    import random

    rng = random.Random(0)
    for _ in range(20):
        assert 1e-4 <= tune.loguniform(1e-4, 1e-1).sample(rng) <= 1e-1
        assert 0 <= tune.uniform(0, 5).sample(rng) <= 5
        assert tune.randint(3, 7).sample(rng) in (3, 4, 5, 6)


def test_asha_stops_bad_trials_unit():
    from ray_tpu.tune.schedulers import ASHAScheduler
    from ray_tpu.tune.trial import Trial

    sched = ASHAScheduler(metric="loss", mode="min", max_t=16,
                          grace_period=2, reduction_factor=2)
    good, bad = Trial(config={}), Trial(config={})
    decisions = []
    for t in range(1, 17):
        for trial, loss in ((good, 0.1 / t), (bad, 5.0)):
            trial.num_results += 1
            d = sched.on_trial_result(trial, {"loss": loss,
                                              "training_iteration": t})
            decisions.append((trial is bad, t, d))
    bad_stopped = any(is_bad and d == "STOP" for is_bad, _, d in decisions)
    good_stopped = any((not is_bad) and d == "STOP" and t < 16
                       for is_bad, t, d in decisions)
    assert bad_stopped and not good_stopped


def _trainable(config):
    for step in range(1, config.get("steps", 8) + 1):
        loss = config["lr"] * 100 + 1.0 / step
        tune.report({"loss": loss, "training_iteration": step})


def test_tuner_fit_random_search(ray_start_shared, tmp_path):
    from ray_tpu.train.config import RunConfig

    tuner = tune.Tuner(
        _trainable,
        param_space={"lr": tune.grid_search([0.001, 0.1]), "steps": 3},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(name="grid", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    assert len(results) == 2
    assert not results.errors
    best = results.get_best_result()
    assert best.config["lr"] == 0.001
    df = results.get_dataframe()
    assert "config/lr" in df.columns and len(df) == 2


@pytest.mark.slow  # ~26s: 10 trials through the 50ms controller poll loop
def test_tuner_asha_10_trials(ray_start_shared, tmp_path):
    from ray_tpu.train.config import RunConfig

    tuner = tune.Tuner(
        _trainable,
        param_space={"lr": tune.loguniform(1e-4, 1e0), "steps": 8},
        tune_config=tune.TuneConfig(
            num_samples=10, metric="loss", mode="min", seed=42,
            scheduler=tune.ASHAScheduler(metric="loss", mode="min",
                                         max_t=8, grace_period=2,
                                         reduction_factor=2)),
        run_config=RunConfig(name="asha", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    assert len(results) == 10
    assert not results.errors
    # ASHA must have early-stopped at least one trial.
    iters = [len(r.metrics_history) for r in results]
    assert min(iters) < max(iters)
    best = results.get_best_result()
    assert best.metrics["loss"] == min(r.metrics["loss"] for r in results
                                       if "loss" in r.metrics)


def test_tuner_checkpoint_and_restore(ray_start_shared, tmp_path):
    from ray_tpu.train.checkpoint import Checkpoint
    from ray_tpu.train.config import RunConfig

    def ckpt_trainable(config):
        start = 0
        ckpt = tune.get_checkpoint()
        if ckpt is not None:
            start = ckpt.to_dict()["step"] + 1
        for step in range(start, 4):
            tune.report({"loss": 1.0 / (step + 1), "step": step},
                        checkpoint=Checkpoint.from_dict({"step": step}))

    exp = str(tmp_path / "resume_exp")
    run = RunConfig(name="resume_exp", storage_path=str(tmp_path))
    tuner = tune.Tuner(ckpt_trainable,
                       param_space={"x": tune.grid_search([1, 2])},
                       tune_config=tune.TuneConfig(metric="loss", mode="min"),
                       run_config=run)
    results = tuner.fit()
    assert not results.errors
    assert tune.Tuner.can_restore(exp)

    # Restore: finished trials stay finished; no errors on refit.
    restored = tune.Tuner.restore(exp, ckpt_trainable)
    results2 = restored.fit()
    assert len(results2) == 2
    assert not results2.errors
    for r in results2:
        assert r.checkpoint is not None
        assert r.checkpoint.to_dict()["step"] == 3


def test_trainer_as_trainable_through_tuner(ray_start_shared, tmp_path):
    """Train -> Tune integration (reference: base_trainer constructs a Tuner)."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig
    from ray_tpu.train.backend import JaxConfig

    def loop(config):
        from ray_tpu.train import session

        session.report({"loss": config.get("lr", 1.0)})

    trainer = JaxTrainer(
        loop,
        jax_config=JaxConfig(distributed=False),
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="tt", storage_path=str(tmp_path / "inner")),
    )
    tuner = tune.Tuner(
        trainer,
        param_space={"lr": tune.grid_search([0.3, 0.7])},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(name="tt_exp", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    assert not results.errors, results.errors
    assert results.get_best_result().config["lr"] == 0.3


@pytest.mark.slow  # ~14s: population rounds through the controller poll loop
def test_pbt_exploits(ray_start_shared, tmp_path):
    from ray_tpu.train.checkpoint import Checkpoint
    from ray_tpu.train.config import RunConfig

    def pbt_trainable(config):
        ckpt = tune.get_checkpoint()
        score = ckpt.to_dict()["score"] if ckpt else 0.0
        for _ in range(8):
            score += config["rate"]
            tune.report({"score": score},
                        checkpoint=Checkpoint.from_dict({"score": score}))

    sched = tune.PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=2,
        hyperparam_mutations={"rate": [0.1, 1.0]}, seed=0)
    run = RunConfig(name="pbt", storage_path=str(tmp_path))
    results = tune.Tuner(
        pbt_trainable,
        param_space={"rate": tune.grid_search([0.1, 0.1, 1.0, 1.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    scheduler=sched),
        run_config=run,
    ).fit()
    assert not results.errors, results.errors
    best = results.get_best_result()
    assert best.metrics["score"] > 0


@pytest.mark.slow  # ~13s: laggard trial must run long enough to be stopped
def test_median_stopping_rule_stops_laggard(ray_start_shared, tmp_path):
    """Trials well under the field's median stop early (reference
    median_stopping_rule.py)."""
    from ray_tpu import tune

    def trainable(config):
        for step in range(12):
            tune.report({"score": config["level"] + step * 0.01})

    tuner = tune.Tuner(
        trainable,
        param_space={"level": tune.grid_search([0.0, 0.0, 10.0, 10.0,
                                                10.0])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max",
            scheduler=tune.MedianStoppingRule(
                metric="score", mode="max", grace_period=2,
                min_samples_required=2)),
        run_config=tune.RunConfig(name="median", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    by_level = {}
    for r in results:
        by_level.setdefault(r.config["level"], []).append(
            r.metrics.get("training_iteration", 0))
    # The high-level trials run to completion; low-level ones cut early.
    assert max(by_level[10.0]) == 12
    assert min(by_level[0.0]) < 12


@pytest.mark.slow  # ~20s: full bracket of trials through the poll loop
def test_hyperband_scheduler_halves(ray_start_shared, tmp_path):
    """HyperBand brackets cut under-performers at their milestones while
    the best survive to max_t."""
    from ray_tpu import tune

    def trainable(config):
        for step in range(9):
            tune.report({"loss": config["quality"] / (step + 1)})

    tuner = tune.Tuner(
        trainable,
        param_space={"quality": tune.grid_search(
            [1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0])},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min",
            scheduler=tune.HyperBandScheduler(
                metric="loss", mode="min", max_t=9, reduction_factor=3)),
        run_config=tune.RunConfig(name="hb", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    iters = {r.config["quality"]: r.metrics.get("training_iteration", 0)
             for r in results}
    # The best config survives to the end; the worst is cut before max_t.
    assert iters[1.0] == 9
    assert iters[128.0] < 9
    best = results.get_best_result()
    assert best.config["quality"] == 1.0
