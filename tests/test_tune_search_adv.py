"""Advanced Tune search: BOHB-style Bayesian searcher + PB2 scheduler.

Reference behavior: `tune/search/bohb/bohb_search.py` (TuneBOHB, paired
with HyperBandForBOHB) and `tune/schedulers/pb2.py` (GP-bandit explore
step for PBT) — both re-implemented natively since hpbandster/GPy are
unavailable here.
"""

import numpy as np
import pytest

from ray_tpu import tune


# --------------------------------------------------------------------------- #
# GP core
# --------------------------------------------------------------------------- #


def test_gp_fits_and_predicts():
    from ray_tpu.tune.schedulers import _GP

    rng = np.random.default_rng(0)
    X = rng.random((30, 2))
    y = np.sin(3 * X[:, 0]) + 0.5 * X[:, 1]
    gp = _GP(lengthscale=0.3).fit(X, y)
    mu, sd = gp.predict(X)
    # Interpolates training points closely, with small uncertainty there.
    assert float(np.abs(mu - y).mean()) < 0.05
    far = np.full((1, 2), 5.0)
    _, sd_far = gp.predict(far)
    assert sd_far[0] > sd.mean()  # uncertainty grows away from data


def test_pb2_perturb_respects_bounds_and_uses_gp():
    from ray_tpu.tune.schedulers import PB2
    from ray_tpu.tune.trial import Trial

    pb2 = PB2(metric="score", mode="max", perturbation_interval=1,
              hyperparam_bounds={"lr": (0.0, 1.0)}, seed=0)
    # Feed interval deltas: reward improves in proportion to lr (the GP
    # should steer suggestions toward high lr).
    trials = [Trial(config={"lr": v}) for v in
              (0.05, 0.2, 0.4, 0.6, 0.8, 0.95)]
    for step in range(1, 5):
        for t in trials:
            t.num_results += 1
            score = step * t.config["lr"]  # higher lr -> faster growth
            pb2.on_trial_result(t, {"score": score})
    assert len(pb2._data) >= 4
    suggestions = [pb2.perturb({"lr": 0.5})["lr"] for _ in range(5)]
    assert all(0.0 <= s <= 1.0 for s in suggestions)
    assert np.mean(suggestions) > 0.6, (
        f"GP-UCB should prefer high lr, got {suggestions}")


def test_pb2_requires_bounds():
    from ray_tpu.tune.schedulers import PB2

    with pytest.raises(ValueError, match="hyperparam_bounds"):
        PB2(metric="score", mode="max")


def test_pb2_cold_start_uniform():
    from ray_tpu.tune.schedulers import PB2

    pb2 = PB2(metric="score", mode="max",
              hyperparam_bounds={"lr": (0.1, 0.2)}, seed=1)
    for _ in range(10):
        v = pb2.perturb({"lr": 0.15})["lr"]
        assert 0.1 <= v <= 0.2


# --------------------------------------------------------------------------- #
# BOHB searcher
# --------------------------------------------------------------------------- #


def test_bohb_models_largest_informative_budget():
    from ray_tpu.tune.search import BOHBSearcher

    s = BOHBSearcher({"x": tune.uniform(0, 1)}, metric="loss", mode="min",
                     n_initial=3, seed=0)
    # 5 observations at budget 1, only 2 at budget 4 -> model budget 1.
    for i in range(5):
        s.on_result({"x": i / 5}, {"loss": i, "training_iteration": 1})
    for i in range(2):
        s.on_result({"x": i / 2}, {"loss": i, "training_iteration": 4})
    assert s._model_history() == list(s._by_budget[1].values())
    # Replaying an iteration must not duplicate (restore/exploit replay).
    s.on_result({"x": 0.0}, {"loss": 5.0, "training_iteration": 4})
    assert len(s._by_budget[4]) == 2
    assert dict(s._by_budget[4])[repr(sorted({"x": 0.0}.items()))][1] == 5.0
    # Third DISTINCT budget-4 observation flips to the higher fidelity.
    s.on_result({"x": 0.9}, {"loss": 0.1, "training_iteration": 4})
    assert s._model_history() == list(s._by_budget[4].values())


def test_bohb_converges_on_quadratic():
    """After seeding, suggestions should concentrate near the optimum of
    a 1-d quadratic (score = (x - 0.7)^2, minimized)."""
    from ray_tpu.tune.search import BOHBSearcher

    s = BOHBSearcher({"x": tune.uniform(0, 1)}, metric="loss", mode="min",
                     n_initial=6, seed=3)
    rng = np.random.default_rng(0)
    for _ in range(30):
        cfg = s.suggest()
        loss = (cfg["x"] - 0.7) ** 2
        s.on_result(cfg, {"loss": loss, "training_iteration": 1})
        s.on_trial_complete(cfg, loss)
    tail = [s.suggest()["x"] for _ in range(10)]
    assert abs(float(np.median(tail)) - 0.7) < 0.2, tail


def test_bohb_with_hyperband_tuner(ray_start_shared, tmp_path):
    """Contract test against the real Tuner machinery: BOHB proposes,
    HyperBand prunes, the best configs cluster near the optimum."""
    from ray_tpu.train.config import RunConfig
    from ray_tpu.tune.schedulers import HyperBandScheduler

    def trainable(config):
        for step in range(1, 5):
            loss = (config["lr"] - 0.3) ** 2 + 0.1 / step
            tune.report({"loss": loss, "training_iteration": step})

    searcher = tune.BOHBSearcher({"lr": tune.uniform(0.0, 1.0)},
                                 metric="loss", mode="min", n_initial=4,
                                 seed=0)
    tuner = tune.Tuner(
        trainable,
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=12,
            search_alg=searcher,
            scheduler=HyperBandScheduler(metric="loss", mode="min",
                                         max_t=4)),
        run_config=RunConfig(name="bohb", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    assert not results.errors
    best = results.get_best_result()
    assert abs(best.config["lr"] - 0.3) < 0.25


def test_pb2_with_tuner(ray_start_shared, tmp_path):
    """PB2 end-to-end: exploit/explore cycles run, mutated lrs stay in
    bounds, and the run finds a low loss."""
    from ray_tpu.train.config import RunConfig

    def trainable(config):
        lr = config["lr"]
        for step in range(1, 9):
            loss = (lr - 0.6) ** 2 + 1.0 / (step + 1)
            tune.report({"loss": loss, "training_iteration": step})

    tuner = tune.Tuner(
        trainable,
        param_space={"lr": tune.uniform(0.0, 1.0)},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=4,
            scheduler=tune.PB2(metric="loss", mode="min",
                               perturbation_interval=2,
                               hyperparam_bounds={"lr": (0.0, 1.0)},
                               seed=0)),
        run_config=RunConfig(name="pb2", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    assert not results.errors
    best = results.get_best_result()
    assert best.metrics["loss"] < 0.5
    for r in results:
        assert 0.0 <= r.config["lr"] <= 1.0
