"""Utility-library tests: ActorPool, Queue.

Coverage mirrors the reference's `python/ray/util/` unit tests.
"""

import pytest

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool


class PoolWorker:
    def __init__(self, slow_on=None):
        self.slow_on = slow_on

    def double(self, x):
        import time

        if self.slow_on is not None and x == self.slow_on:
            time.sleep(0.3)
        return 2 * x


def _make_pool(n=2, **kw):
    cls = ray_tpu.remote(PoolWorker)
    return ActorPool([cls.remote(**kw) for _ in range(n)])


def test_actor_pool_map_ordered(ray_start_shared):
    pool = _make_pool(2)
    assert list(pool.map(lambda a, v: a.double.remote(v), range(8))) == [
        2 * i for i in range(8)]


def test_actor_pool_map_unordered_completes_all(ray_start_shared):
    # Item 0 is slow on one actor: unordered results must still be complete,
    # and a fast item should be able to finish before the slow one.
    pool = _make_pool(2, slow_on=0)
    out = list(pool.map_unordered(lambda a, v: a.double.remote(v), range(6)))
    assert sorted(out) == [2 * i for i in range(6)]


def test_actor_pool_backlog_exceeds_actors(ray_start_shared):
    # More submissions than actors: backlog drains as actors free up.
    pool = _make_pool(2)
    for i in range(10):
        pool.submit(lambda a, v: a.double.remote(v), i)
    results = []
    while pool.has_next():
        results.append(pool.get_next())
    assert results == [2 * i for i in range(10)]
    assert not pool.has_next()
    with pytest.raises(StopIteration):
        pool.get_next()


def test_actor_pool_get_next_unordered_empty_raises(ray_start_shared):
    pool = _make_pool(1)
    with pytest.raises(StopIteration):
        pool.get_next_unordered()


def test_actor_pool_push_pop_idle(ray_start_shared):
    pool = _make_pool(2)
    a = pool.pop_idle()
    assert a is not None
    assert pool.has_free()  # one left
    pool.push(a)
    assert len(pool._free) == 2
