"""Worker forge: fork-safety contract, granted-env propagation, cold
fallback + background restart, event-driven death detection, and orphan
hygiene after node stop (the /proc-scan idiom from the JobManager tests).

Process model under test: ONE template per driver process (shared by
every in-process raylet, reused across clusters), carrying a
``--tag rtpuforge-<driver pid>`` argv marker that every forked worker
inherits. The template itself legitimately lingers after Node.stop (it
self-exits on idle or parent death); its CHILDREN — the forked workers —
must not, and cold workers carry RAY_TPU_SESSION in their exec-time
environ for the same scan."""

import os
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.core.worker_forge import WorkerForge, process_tag


def _template_pids(tag: str):
    """Pids whose /proc cmdline carries the forge tag — the template plus
    any forked worker (children inherit argv). A zombie has an empty
    cmdline, so killed-but-unreaped processes cannot false-positive."""
    pids = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                if tag.encode() in f.read():
                    pids.append(int(pid))
        except OSError:
            continue  # exited while scanning
    return pids


def _children_of(ppids):
    """Pids whose parent is in `ppids` (forked workers are children of
    the template)."""
    out = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/stat") as f:
                fields = f.read().rsplit(")", 1)[1].split()
            if int(fields[1]) in ppids:
                out.append(int(pid))
        except (OSError, IndexError, ValueError):
            continue
    return out


def _session_worker_pids(mark: str):
    """Cold-exec workers: RAY_TPU_SESSION=<mark> in the exec-time
    environ."""
    pids = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/environ", "rb") as f:
                if f"RAY_TPU_SESSION={mark}".encode() in f.read():
                    pids.append(int(pid))
        except OSError:
            continue
    return pids


@pytest.fixture(scope="module")
def forge_cluster():
    """Module-scoped single-node cluster with a ready forge."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    raylet = ray_tpu._global_node.raylet
    assert raylet.forge is not None, "forge should be enabled by default"
    assert raylet.forge.wait_ready(60), "forge template never became ready"
    created = ray_tpu._global_runtime
    yield raylet
    if ray_tpu._global_runtime is created:
        ray_tpu.shutdown()


def test_template_fork_safety(forge_cluster):
    """The template must be fork-safe at all times: exactly one thread
    (no RPC clients, no pools) and no initialized XLA backend client."""
    st = forge_cluster.forge.status()
    assert st["threads"] == 1, f"template grew threads: {st}"
    assert not st["xla_initialized"], "template initialized an XLA backend"
    assert "ray_tpu.core.worker" in st["preimported"]
    assert not st["import_errors"], st["import_errors"]


def test_forge_spawn_registers_and_serves(forge_cluster):
    """A forge fork registers like a cold worker and executes tasks; the
    fork path lands well under the cold exec path."""
    pool = forge_cluster.pool

    t0 = time.perf_counter()
    h = pool.spawn_worker(env_extra={}, kind="forge")
    assert h.registered.wait(30) and h.conn is not None
    forge_ms = (time.perf_counter() - t0) * 1e3
    assert h.spawn_kind == "forge"

    t0 = time.perf_counter()
    h2 = pool.spawn_worker(env_extra={}, kind="cold")
    assert h2.registered.wait(60) and h2.conn is not None
    cold_ms = (time.perf_counter() - t0) * 1e3
    assert h2.spawn_kind == "cold"

    # The mechanism claim, with CI-load headroom: fork skips the import
    # bill, so it must land under the exec path.
    assert forge_ms < cold_ms, (forge_ms, cold_ms)

    for h_ in (h, h2):
        pool.mark_dead(h_.worker_id)
        h_.proc.terminate()


def test_granted_env_reaches_forked_worker(forge_cluster):
    """runtime_env env_vars ride the granted env into the forked child
    (applied post-fork, before the worker connects)."""
    pool = forge_cluster.pool
    before = pool.spawn_counts["forge"]

    @ray_tpu.remote
    def read_env():
        return os.environ.get("FORGE_PROBE"), os.getpid()

    val, pid = ray_tpu.get(
        read_env.options(
            runtime_env={"env_vars": {"FORGE_PROBE": "x42"}}).remote(),
        timeout=60)
    assert val == "x42"
    assert pool.spawn_counts["forge"] > before, \
        "granted-env spawn should have taken the forge path"
    handles = [h for h in pool._workers.values() if h.pid == pid]
    assert handles and handles[0].spawn_kind == "forge"


@pytest.mark.parametrize("kind", ["forge", "cold"])
def test_dead_worker_detection_is_event_driven(forge_cluster, kind):
    """A killed worker is marked dead in well under the 2s reaper poll:
    forge forks via the template's exit-event stream, cold spawns via the
    per-process waiter thread (plus the connection-loss path for both)."""
    pool = forge_cluster.pool
    h = pool.spawn_worker(env_extra={}, kind=kind)
    assert h.registered.wait(60) and h.conn is not None
    t0 = time.perf_counter()
    h.proc.kill()  # SIGKILL: no graceful-exit help from the worker
    while h.state != "dead" and time.perf_counter() - t0 < 5:
        time.sleep(0.01)
    elapsed = time.perf_counter() - t0
    assert h.state == "dead"
    assert elapsed < 1.5, f"{kind} death took {elapsed:.2f}s (poll-bound?)"


def test_forge_death_falls_back_cold_then_restarts():
    """Killing the template must not fail spawns (cold fallback) and the
    forge must come back in the background; TPU-style grants always cold."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    try:
        raylet = ray_tpu._global_node.raylet
        forge = raylet.forge
        assert forge.wait_ready(60)
        assert not WorkerForge.compatible({"RAY_TPU_GRANTED_TPU": "1"})

        gen = forge.generation
        forge.proc.kill()
        # The first spawn may race the death notice; either way it must
        # produce a working worker (forge fork from the old incarnation or
        # cold fallback) and trigger the background restart.
        h = raylet.pool.spawn_worker(env_extra={})
        assert h.registered.wait(60) and h.conn is not None
        deadline = time.monotonic() + 60
        while not forge.alive and time.monotonic() < deadline:
            forge.restart_async()
            time.sleep(0.2)
        assert forge.alive and forge.generation >= gen, "forge never restarted"
        h2 = raylet.pool.spawn_worker(env_extra={})
        assert h2.registered.wait(60) and h2.spawn_kind == "forge"
    finally:
        ray_tpu.shutdown()


def test_no_orphan_workers_after_shutdown():
    """Node.stop() leaves no worker behind: no forked children of the
    template, no cold-exec workers for the session (JobManager orphan
    idiom, /proc scan). The template itself may linger — it is
    process-shared and self-reaps (see test_template_dies_with_driver)."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    raylet = ray_tpu._global_node.raylet
    assert raylet.forge.wait_ready(60)
    mark = raylet.session_suffix
    tag = process_tag()

    @ray_tpu.remote
    class Probe:
        def pid(self):
            return os.getpid()

    a = Probe.remote()
    ray_tpu.get(a.pid.remote(), timeout=60)

    @ray_tpu.remote
    def task_pid():
        return os.getpid()

    ray_tpu.get(task_pid.remote(), timeout=60)
    templates = _template_pids(tag)
    assert templates, "expected a live forge template"

    def leaked():
        return _children_of(set(templates)) + _session_worker_pids(mark)

    assert leaked(), "expected live workers while the cluster is up"
    ray_tpu.shutdown()
    deadline = time.monotonic() + 10
    while leaked() and time.monotonic() < deadline:
        time.sleep(0.2)
    assert leaked() == [], f"orphaned workers after shutdown: {leaked()}"


def test_template_dies_with_driver():
    """A lingering template must not outlive the process that spawned it
    (ppid guard): a short-lived driver's template self-reaps."""
    code = (
        "import ray_tpu, os\n"
        "ray_tpu.init(num_cpus=1)\n"
        "ray_tpu._global_node.raylet.forge.wait_ready(60)\n"
        "from ray_tpu.core.worker_forge import process_tag\n"
        "print(process_tag(), flush=True)\n"
        # exit WITHOUT shutdown: the hard case — nobody detaches cleanly
        "os._exit(0)\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=120)
    tag = proc.stdout.strip().splitlines()[-1]
    assert tag.startswith("rtpuforge-"), proc.stderr[-500:]
    deadline = time.monotonic() + 10
    while _template_pids(tag) and time.monotonic() < deadline:
        time.sleep(0.25)
    assert _template_pids(tag) == [], \
        "template outlived its driver process"
