"""Functions invoked BY NAME from the C++ client through the xlang
gateway (tests/test_cpp_client.py). Must be importable on workers —
tests run with the repo root on PYTHONPATH, which spawn_worker
propagates."""


def add(a, b):
    return a + b


def boom():
    raise RuntimeError("deliberate xlang failure")
